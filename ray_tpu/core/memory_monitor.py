"""Node memory watermark monitor + OOM worker-killing policies.

TPU-native analogue of the reference's ``MemoryMonitor``
(``src/ray/common/memory_monitor.h:52``) and its worker-killing policies
(``src/ray/raylet/worker_killing_policy_retriable_fifo.cc``,
``worker_killing_policy_group_by_owner.cc``): when the node's memory usage
crosses ``memory_usage_threshold``, one worker is killed per check (with a
cooldown) to shed load before the OS OOM killer takes the whole node down.

Policy order mirrors the reference's intent:

* idle pooled workers go first — they hold interpreter memory but no task,
  so killing them is pure relief;
* then ``retriable_fifo``: the most recently leased *retriable* task worker
  (its owner resubmits; older tasks keep their progress);
* ``group_by_owner`` instead prefers the owner with the most leased workers
  on this node (sheds the biggest contributor's newest task first);
* a non-retriable worker is killed only as a last resort — its owner
  surfaces :class:`ray_tpu.core.errors.OutOfMemoryError` (the node recorded
  the death cause, see ``Node.worker_death_cause``).

Usage is read from cgroup v2 limits when present (containers), else
``/proc/meminfo`` (used = MemTotal - MemAvailable). Tests inject a fake
reader via ``MemoryMonitor.set_reader``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, List, Optional, Tuple

from ray_tpu.core.config import config
from ray_tpu.util.ratelimit import log_every

logger = logging.getLogger(__name__)

Reading = Tuple[int, int]  # (used_bytes, total_bytes)


def default_memory_reader() -> Reading:
    """Cgroup-v2-aware node memory usage; falls back to /proc/meminfo."""
    try:
        with open("/sys/fs/cgroup/memory.max") as f:
            limit = f.read().strip()
        if limit != "max":
            with open("/sys/fs/cgroup/memory.current") as f:
                used = int(f.read().strip())
            # Page cache in memory.current is reclaimable — counting it
            # would OOM-kill workers during heavy file I/O (the reference
            # subtracts inactive_file for exactly this reason,
            # memory_monitor.cc GetCGroupMemoryUsedBytes).
            try:
                with open("/sys/fs/cgroup/memory.stat") as f:
                    for line in f:
                        if line.startswith("inactive_file "):
                            used -= int(line.split()[1])
                            break
            except (OSError, ValueError):
                pass
            return max(0, used), int(limit)
    except (OSError, ValueError):
        pass
    total = avail = 0
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
                if total and avail:
                    break
    except OSError:
        return 0, 0
    return max(0, total - avail), total


def pick_victim(handles: List, policy: str) -> Optional[object]:
    """Choose one worker handle to kill. ``handles`` is a snapshot of the
    node's live :class:`WorkerHandle` objects; returns a handle or None.
    Pure function of the snapshot so the selection logic is unit-testable
    without a node (the reference tests its policies the same way)."""
    alive = [h for h in handles if h.proc.poll() is None]
    # 1. Idle pooled workers: no task aboard, cheapest relief.
    idle = [h for h in alive if h.idle]
    if idle:
        return min(idle, key=lambda h: h.last_used)  # oldest idle first
    leased = [h for h in alive
              if h.lease_resources is not None and not h.dedicated]
    if not leased:
        return None

    def retriable(h) -> bool:
        return bool((getattr(h, "task_meta", None) or {}).get(
            "retriable", True))

    if policy == "group_by_owner":
        groups = {}
        for h in leased:
            owner = (getattr(h, "task_meta", None) or {}).get("owner", "")
            groups.setdefault(owner, []).append(h)
        # Largest group sheds first; retriable groups preferred at equal size.
        ordered = sorted(
            groups.values(),
            key=lambda g: (len(g), sum(retriable(h) for h in g)),
            reverse=True)
        group = ordered[0]
        pick = [h for h in group if retriable(h)] or group
        return max(pick, key=lambda h: h.last_used)  # newest in group
    # retriable_fifo (default): newest retriable lease; non-retriable only
    # as a last resort (also newest-first).
    pool = [h for h in leased if retriable(h)] or leased
    return max(pool, key=lambda h: h.last_used)


class MemoryMonitor:
    """Background watermark check attached to a :class:`Node`."""

    def __init__(self, node, reader: Optional[Callable[[], Reading]] = None):
        self._node = node
        self._reader = reader or default_memory_reader
        self._stopped = threading.Event()
        self._last_kill = 0.0
        self.kills: List[dict] = []  # bounded history for get_info/tests
        self.total_kills = 0  # monotonic; history above is trimmed
        self._thread = threading.Thread(
            target=self._loop, name="memory-monitor", daemon=True)
        self._thread.start()

    def set_reader(self, reader: Callable[[], Reading]) -> None:
        self._reader = reader

    def stop(self) -> None:
        self._stopped.set()

    def _loop(self) -> None:
        period = config.memory_monitor_refresh_s
        while not self._stopped.wait(period):
            try:
                self.check_once()
            except Exception:
                # A monitor that fails every tick means NO oom
                # protection — keep running, but say so.
                log_every("memory_monitor.check", 60.0, logger,
                          "memory watermark check failed", exc_info=True)

    def check_once(self) -> Optional[bytes]:
        """One watermark check; returns the killed worker id (or None)."""
        used, total = self._reader()
        if total <= 0 or used / total < config.memory_usage_threshold:
            return None
        now = time.monotonic()
        if now - self._last_kill < config.memory_kill_interval_s:
            return None
        with self._node._lock:
            handles = list(self._node._workers.values())
        victim = pick_victim(handles, config.worker_killing_policy)
        if victim is None:
            return None
        self._last_kill = now
        reason = (f"memory monitor: node memory {used}/{total} "
                  f"({used / total:.0%}) above threshold "
                  f"{config.memory_usage_threshold:.0%}")
        self.kills.append({"worker": victim.worker_id.hex(), "ts": time.time(),
                           "used": used, "total": total,
                           "retriable": bool((getattr(victim, "task_meta",
                                                      None) or {}).get(
                               "retriable", True))})
        del self.kills[:-100]
        self.total_kills += 1
        self._node.kill_worker(victim.worker_id.binary(), force=True,
                               reason=reason)
        return victim.worker_id.binary()
