"""RPC transport: length-prefixed pickle frames over localhost TCP.

Fills the role of the reference's gRPC layer (``src/ray/rpc/grpc_server.h``,
``grpc_client.h``) for every process boundary in the runtime: driver <->
controller, node <-> controller, owner <-> worker (task push), worker <->
node. The wire format is deliberately minimal — an 8-byte big-endian length
prefix followed by a pickled message dict — because on a TPU VM every hop is
localhost or DCN-with-TLS-terminated-elsewhere; there is no cross-language
requirement (the reference needs protobuf for its Java/C++ frontends).

Concurrency model: ``RpcServer`` runs one accept thread, one reader thread per
connection, and dispatches each request to a shared thread pool so a blocking
handler (e.g. task execution) never head-of-line-blocks control messages on
the same connection. ``RpcClient`` multiplexes concurrent in-flight calls over
one socket with a response-reader thread, mirroring the async client-call
pattern of ``src/ray/rpc/client_call.h``.
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional, Tuple

import cloudpickle

from ray_tpu.core.config import config

Addr = Tuple[str, int]

_LEN = struct.Struct(">Q")


def dumps(obj: Any) -> bytes:
    """Pickle with cloudpickle fallback for closures/lambdas/local classes."""
    try:
        return pickle.dumps(obj, protocol=5)
    except Exception:
        return cloudpickle.dumps(obj, protocol=5)


def loads(data) -> Any:
    return pickle.loads(data)


# Out-of-band frame layout (PEP 574): MAGIC | u32 meta_len | u32 n_bufs |
# u64 sizes[n] | meta | raw buffers. Large buffer-protocol payloads (numpy
# chunks, actor args) skip the pickle byte-copy on BOTH ends: the sender
# scatter-writes the raw buffers, the receiver reconstructs zero-copy
# views into the single recv buffer. \xff can never begin a plain pickle
# (those start with \x80 PROTO), so the magic is unambiguous.
_OOB_MAGIC = b"\xffRTB1"


def dumps_parts(obj: Any) -> list:
    """Serialize to a list of send buffers (scatter-gather). Falls back to
    one in-band pickle part for cloudpickle payloads and non-contiguous
    buffers."""
    bufs: list = []
    try:
        meta = pickle.dumps(obj, protocol=5, buffer_callback=bufs.append)
        raws = [b.raw() for b in bufs]
    except Exception:
        return [dumps(obj)]
    if not raws:
        return [meta]
    head = b"".join([_OOB_MAGIC,
                     struct.pack("<II", len(meta), len(raws)),
                     struct.pack(f"<{len(raws)}Q",
                                 *(r.nbytes for r in raws)),
                     meta])
    return [head] + raws


def loads_frame(frame) -> Any:
    view = memoryview(frame)
    if bytes(view[:len(_OOB_MAGIC)]) != _OOB_MAGIC:
        return pickle.loads(view)
    off = len(_OOB_MAGIC)
    meta_len, n = struct.unpack_from("<II", view, off)
    off += 8
    sizes = struct.unpack_from(f"<{n}Q", view, off)
    off += 8 * n
    meta = view[off:off + meta_len]
    off += meta_len
    buffers = []
    for s in sizes:
        buffers.append(view[off:off + s])
        off += s
    return pickle.loads(meta, buffers=buffers)


def _struct_pack_timeval(seconds: int) -> bytes:
    import struct as _struct

    return _struct.pack("ll", seconds, 0)


def send_frame(sock: socket.socket, payload) -> None:
    if isinstance(payload, (bytes, bytearray)):
        _chaos_gate(sock, len(payload))
        sock.sendall(_LEN.pack(len(payload)) + payload)
        return
    # Scatter path: length header, then parts in order. Small parts
    # coalesce into one syscall; big buffers go straight from their
    # backing memory (an mmap'd store chunk never lands in a pickle copy).
    total = sum(memoryview(p).nbytes for p in payload)
    _chaos_gate(sock, total)
    head = bytearray(_LEN.pack(total))
    for p in payload:
        if memoryview(p).nbytes < 65536 and len(head) < (1 << 20):
            head += p
        else:
            if head:
                sock.sendall(head)
                head = bytearray()
            sock.sendall(p)
    if head:
        sock.sendall(head)


def recv_exact(sock: socket.socket, n: int) -> memoryview:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], min(n - got, 8 << 20))
        if r == 0:
            raise ConnectionError("socket closed mid-frame")
        got += r
    return view


def recv_frame(sock: socket.socket) -> memoryview:
    header = recv_exact(sock, _LEN.size)
    (length,) = _LEN.unpack(header)
    return recv_exact(sock, length)


# Network-chaos injection seam (reference: tc-based latency/bandwidth
# chaos, tests/chaos/chaos_network_delay.yaml + chaos_network_bandwidth
# .yaml — here in-process so the multi-node-in-one-machine fixture can
# exercise slow/lossy links without root/tc). Applied on the CLIENT send
# path of the process that called set_network_chaos (per-process, like tc
# on one host's egress).
_chaos = {"delay_s": 0.0, "jitter_s": 0.0, "drop_prob": 0.0, "rng": None,
          "bandwidth_bps": 0.0}


def set_network_chaos(delay_ms: float = 0.0, jitter_ms: float = 0.0,
                      drop_prob: float = 0.0,
                      bandwidth_mbps: float = 0.0, seed: int = 0) -> None:
    """Inject latency/jitter/loss/bandwidth limits into every outbound RPC
    of THIS process. ``drop_prob`` drops the send by severing the
    connection (the peer sees a reset — exercising the same reconnect
    paths a flaky network does). Zero everything to disable."""
    import random as _random

    _chaos.update(delay_s=delay_ms / 1e3, jitter_s=jitter_ms / 1e3,
                  drop_prob=drop_prob,
                  bandwidth_bps=bandwidth_mbps * 125_000.0,
                  rng=_random.Random(seed))


def _chaos_gate(sock: socket.socket, nbytes: int) -> None:
    if _chaos["rng"] is None:
        return
    if _chaos["drop_prob"] and _chaos["rng"].random() < _chaos["drop_prob"]:
        try:
            sock.close()
        except OSError:
            pass
        raise OSError("chaos: connection dropped")
    delay = _chaos["delay_s"]
    if _chaos["jitter_s"]:
        delay += _chaos["rng"].uniform(0.0, _chaos["jitter_s"])
    if _chaos["bandwidth_bps"]:
        delay += nbytes / _chaos["bandwidth_bps"]
    if delay > 0:
        time.sleep(delay)


class RpcError(Exception):
    """Transport-level failure (peer died, connection refused)."""


class RemoteCallError(Exception):
    """The handler on the peer raised; carries the remote exception."""

    def __init__(self, cause: BaseException):
        self.cause = cause
        super().__init__(repr(cause))


class RpcServer:
    """Threaded request/response server.

    ``handlers`` maps method name -> callable(*args, **kwargs). Handlers run
    on a thread pool; their return value (or raised exception) is shipped back
    to the caller. A request with ``id is None`` is a one-way notification.
    """

    def __init__(
        self,
        handlers: Dict[str, Callable],
        host: str = "127.0.0.1",
        port: int = 0,
        name: str = "rpc",
        max_workers: int = 64,
        inline_methods: Optional[set] = None,
    ):
        self._handlers = dict(handlers)
        # Methods run directly on the connection reader thread instead of the
        # shared pool. Use for quick, never-blocking handlers that must make
        # progress even when the pool is saturated with blocking calls (e.g.
        # a node's return_worker while many lease_worker calls wait).
        self._inline = set(inline_methods or ())
        self._name = name
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(256)
        self.addr: Addr = self._sock.getsockname()
        self._pool = ThreadPoolExecutor(max_workers=max_workers,
                                        thread_name_prefix=f"{name}-h")
        self._stopped = threading.Event()
        self._conns: list[socket.socket] = []
        self._conns_lock = threading.Lock()
        # Reactor, not thread-per-connection: ONE selector thread reads
        # every connection (a 5,000-worker fleet means 5,000 inbound
        # sockets on a node/controller — a reader thread each breaks the
        # process's thread/mmap budget long before CPU does). Inline
        # methods run on the reactor; the rest dispatch to the pool.
        import selectors as _selectors

        self._selector = _selectors.DefaultSelector()
        # The listening socket lives in the same selector (data=None
        # marks it): one thread accepts AND reads — at 5,000 workers per
        # box, every thread per process counts against kernel.pid_max.
        self._sock.setblocking(False)
        self._selector.register(self._sock, 1, None)
        self._reactor_thread = threading.Thread(
            target=self._reactor, name=f"{name}-reactor", daemon=True)
        self._reactor_thread.start()

    def register(self, method: str, fn: Callable) -> None:
        self._handlers[method] = fn

    class _Conn:
        __slots__ = ("sock", "buf", "send_lock")

        def __init__(self, sock):
            self.sock = sock
            self.buf = bytearray()
            self.send_lock = threading.Lock()

    def _accept(self) -> None:
        while True:
            try:
                conn, _ = self._sock.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # Bounded sends: inline replies go out on the reactor thread,
            # and an unbounded sendall to one stalled peer would freeze
            # EVERY connection. A send that can't complete in 15s drops
            # the peer (partial frame = torn stream, the conn must die).
            conn.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                            _struct_pack_timeval(15))
            with self._conns_lock:
                self._conns.append(conn)
            try:
                self._selector.register(conn, 1,  # EVENT_READ
                                        RpcServer._Conn(conn))
            except (OSError, ValueError):
                pass

    def _drop(self, st: "_Conn") -> None:
        try:
            self._selector.unregister(st.sock)
        except (KeyError, OSError, ValueError):
            pass
        try:
            st.sock.close()
        except OSError:
            pass
        with self._conns_lock:
            if st.sock in self._conns:
                self._conns.remove(st.sock)

    def _reactor(self) -> None:
        while not self._stopped.is_set():
            try:
                events = self._selector.select(timeout=0.5)
            except OSError:
                return
            for key, _mask in events:
                st = key.data
                if st is None:  # the listening socket
                    self._accept()
                    continue
                try:
                    # Blocking socket + MSG_DONTWAIT: reads never park the
                    # reactor, writes (replies) stay simple blocking sends.
                    data = st.sock.recv(1 << 20, socket.MSG_DONTWAIT)
                except (BlockingIOError, InterruptedError):
                    continue
                except OSError:
                    self._drop(st)
                    continue
                if not data:
                    self._drop(st)
                    continue
                st.buf += data
                self._pump(st)

    def _pump(self, st: "_Conn") -> None:
        """Dispatch every complete frame buffered on the connection."""
        hdr = _LEN.size
        while True:
            if len(st.buf) < hdr:
                return
            (length,) = _LEN.unpack_from(st.buf)
            if len(st.buf) < hdr + length:
                return
            frame = bytes(st.buf[hdr:hdr + length])
            del st.buf[:hdr + length]
            try:
                msg = loads_frame(memoryview(frame))
            except Exception:
                self._drop(st)
                return
            if msg.get("method") in self._inline:
                self._handle(st.sock, st.send_lock, msg)
            else:
                try:
                    self._pool.submit(self._handle, st.sock, st.send_lock,
                                      msg)
                except RuntimeError:
                    # Pool shut down while a request was in flight:
                    # server stopping, or interpreter exit (the
                    # concurrent.futures atexit hook kills all pools
                    # before daemon threads die). Drop the request.
                    self._drop(st)
                    return

    def _handle(self, conn, send_lock, msg) -> None:
        req_id = msg.get("id")
        try:
            handler = self._handlers[msg["method"]]
            result = handler(*msg.get("args", ()), **msg.get("kwargs", {}))
            reply = {"id": req_id, "ok": True, "result": result}
        except BaseException as e:  # noqa: BLE001 — errors must reach the caller
            reply = {"id": req_id, "ok": False, "error": e}
        if req_id is None:
            return
        try:
            payload = dumps_parts(reply)
        except Exception as e:
            payload = dumps({"id": req_id, "ok": False,
                             "error": RpcError(f"unpicklable reply: {e!r}")})
        try:
            with send_lock:
                send_frame(conn, payload)
        except OSError:
            # A failed/timed-out send may have written a PARTIAL frame —
            # the stream is torn, so the connection must die (the
            # reactor's next recv observes the close and unregisters it).
            try:
                conn.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._stopped.set()
        # Wake the accept thread: a thread blocked in accept() holds a
        # kernel reference to the listening socket, so close() alone leaves
        # the port bound (a restarted peer could never rebind the same
        # address). A self-connect makes accept() return; the loop then
        # sees _stopped and exits, releasing the fd for real.
        try:
            with socket.create_connection(self.addr, timeout=1.0):
                pass
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._reactor_thread.join(timeout=2.0)
        with self._conns_lock:
            for c in self._conns:
                try:
                    c.close()
                except OSError:
                    pass
        try:
            self._selector.close()
        except (OSError, RuntimeError):
            pass
        self._pool.shutdown(wait=False)


class RpcClient:
    """Client multiplexing concurrent calls over one TCP connection."""

    def __init__(self, addr: Addr, connect_timeout: Optional[float] = None):
        self.addr = tuple(addr)
        self._sock = _connect(self.addr, connect_timeout)
        self._send_lock = threading.Lock()
        self._next_id = 0
        self._id_lock = threading.Lock()
        self._pending: Dict[int, _PendingCall] = {}
        self._pending_lock = threading.Lock()
        self._closed = False
        self._reader = threading.Thread(target=self._read_loop,
                                        name="rpc-client-read", daemon=True)
        self._reader.start()

    def _read_loop(self) -> None:
        try:
            while True:
                msg = loads_frame(recv_frame(self._sock))
                with self._pending_lock:
                    call = self._pending.pop(msg["id"], None)
                if call is not None:
                    call.complete(msg)
        except (ConnectionError, OSError):
            self._fail_all(RpcError(f"connection to {self.addr} lost"))

    def _fail_all(self, err: Exception) -> None:
        self._closed = True
        with self._pending_lock:
            pending, self._pending = self._pending, {}
        for call in pending.values():
            call.fail(err)

    def call(self, method: str, *args, timeout: Optional[float] = None, **kwargs):
        if self._closed:
            raise RpcError(f"client to {self.addr} is closed")
        with self._id_lock:
            self._next_id += 1
            req_id = self._next_id
        call = _PendingCall()
        with self._pending_lock:
            self._pending[req_id] = call
        payload = dumps_parts({"id": req_id, "method": method,
                               "args": args, "kwargs": kwargs})
        try:
            with self._send_lock:
                send_frame(self._sock, payload)
        except OSError as e:
            with self._pending_lock:
                self._pending.pop(req_id, None)
            self._fail_all(RpcError(f"send to {self.addr} failed: {e}"))
            raise RpcError(f"send to {self.addr} failed: {e}") from e
        try:
            return call.wait(timeout)
        except TimeoutError:
            with self._pending_lock:
                self._pending.pop(req_id, None)
            raise

    def notify(self, method: str, *args, **kwargs) -> None:
        """Fire-and-forget one-way message."""
        payload = dumps_parts({"id": None, "method": method,
                               "args": args, "kwargs": kwargs})
        try:
            with self._send_lock:
                send_frame(self._sock, payload)
        except OSError as e:
            raise RpcError(f"send to {self.addr} failed: {e}") from e

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


class _PendingCall:
    __slots__ = ("_event", "_msg", "_err")

    def __init__(self):
        self._event = threading.Event()
        self._msg = None
        self._err = None

    def complete(self, msg) -> None:
        self._msg = msg
        self._event.set()

    def fail(self, err: Exception) -> None:
        self._err = err
        self._event.set()

    def wait(self, timeout: Optional[float]):
        if not self._event.wait(timeout):
            raise TimeoutError("RPC call timed out")
        if self._err is not None:
            raise self._err
        if not self._msg["ok"]:
            err = self._msg["error"]
            raise RemoteCallError(err) from err
        return self._msg["result"]


def _connect(addr: Addr, timeout: Optional[float]) -> socket.socket:
    retries = config.rpc_connect_retries
    deadline = None if timeout is None else time.monotonic() + timeout
    last_err: Optional[Exception] = None
    for _ in range(max(1, retries)):
        try:
            sock = socket.create_connection(addr, timeout=5.0)
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return sock
        except OSError as e:
            last_err = e
            if deadline is not None and time.monotonic() > deadline:
                break
            time.sleep(0.05)
    raise RpcError(f"could not connect to {addr}: {last_err}")


class ReconnectingClient:
    """Controller-facing client that survives peer restarts.

    The reference's GCS client retries RPCs with backoff while the GCS is
    down and reconnects when it returns (``gcs_rpc_client.h`` retry loop);
    this is that behavior for the framed-pickle transport: on a transport
    error the socket is re-established and the call retried until
    ``retry_window_s`` elapses. Only use against the controller — its
    handlers are idempotent by design (re-register, kv_put, heartbeat,
    create_placement_group 2PC)."""

    def __init__(self, addr: Addr, retry_window_s: float = 10.0):
        self.addr = tuple(addr)
        self._window = retry_window_s
        self._client: Optional[RpcClient] = None
        self._lock = threading.Lock()
        self._closed = False

    def _get(self) -> RpcClient:
        with self._lock:
            if self._closed:
                raise RpcError(f"client to {self.addr} is closed")
            if self._client is None or self._client._closed:
                self._client = RpcClient(self.addr)
            return self._client

    def call(self, method: str, *args, timeout: Optional[float] = None,
             **kwargs):
        deadline = time.monotonic() + self._window
        while True:
            try:
                return self._get().call(method, *args, timeout=timeout,
                                        **kwargs)
            except TimeoutError:
                # A per-call timeout on a healthy connection is the
                # caller's latency bound, not a transport failure —
                # resending would both break the bound and duplicate the
                # request (TimeoutError subclasses OSError since 3.10, so
                # this arm must precede the transport arm).
                raise
            except (RpcError, ConnectionError, OSError):
                if self._closed or time.monotonic() > deadline:
                    raise
                time.sleep(0.2)

    def notify(self, method: str, *args, **kwargs) -> None:
        """Best-effort one-way send (no retry: notifications are periodic)."""
        self._get().notify(method, *args, **kwargs)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._client is not None:
                self._client.close()


class ClientPool:
    """Caches one RpcClient per address; thread-safe, LRU-capped.

    Mirrors the reference's per-address gRPC client caching in the core
    worker (``core_worker_client_pool.h``, incl. its idle-connection
    reclaim). The cap matters at actor-surge scale: every cached client
    owns a reader THREAD, and a driver talking to thousands of actor workers
    would otherwise hold 5,000 threads/connections — past
    vm.max_map_count that breaks thread creation process-wide. Only
    clients with no in-flight calls are evicted; reconnecting later is a
    cheap localhost dial.
    """

    def __init__(self, max_clients: int = 1024):
        from collections import OrderedDict

        self._clients: "OrderedDict[Addr, RpcClient]" = OrderedDict()
        self._max = max_clients
        self._lock = threading.Lock()

    def get(self, addr: Addr) -> RpcClient:
        import time as _time

        addr = tuple(addr)
        evicted: List[RpcClient] = []
        now = _time.monotonic()
        with self._lock:
            client = self._clients.get(addr)
            if client is not None and not client._closed:
                self._clients.move_to_end(addr)
                client._last_handout = now
                return client
            client = RpcClient(addr)
            client._last_handout = now
            self._clients[addr] = client
            if len(self._clients) > self._max:
                for key in list(self._clients):
                    if len(self._clients) <= self._max:
                        break
                    if key == addr:
                        continue
                    cand = self._clients[key]
                    # Evict only clients that are idle AND haven't been
                    # handed out recently: a thread that just got this
                    # client may not have registered its call yet, and a
                    # point-in-time _pending check alone would close the
                    # connection under it.
                    if (not cand._pending
                            and now - getattr(cand, "_last_handout", 0.0)
                            > 5.0):
                        del self._clients[key]
                        evicted.append(cand)
        for c in evicted:
            c.close()
        return client

    def invalidate(self, addr: Addr) -> None:
        with self._lock:
            client = self._clients.pop(tuple(addr), None)
        if client is not None:
            client.close()

    def close_all(self) -> None:
        with self._lock:
            clients, self._clients = list(self._clients.values()), {}
        for c in clients:
            c.close()
