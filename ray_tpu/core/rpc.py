"""RPC transport: length-prefixed pickle frames over localhost TCP.

Fills the role of the reference's gRPC layer (``src/ray/rpc/grpc_server.h``,
``grpc_client.h``) for every process boundary in the runtime: driver <->
controller, node <-> controller, owner <-> worker (task push), worker <->
node. The wire format is deliberately minimal — an 8-byte big-endian length
prefix followed by a pickled message dict — because on a TPU VM every hop is
localhost or DCN-with-TLS-terminated-elsewhere; there is no cross-language
requirement (the reference needs protobuf for its Java/C++ frontends).

Concurrency model: ``RpcServer`` is a single-threaded reactor. ONE selector
thread accepts and reads every connection; inline methods run on the reactor,
the rest dispatch to a shared thread pool so a blocking handler (e.g. task
execution) never head-of-line-blocks control messages on the same connection.

Write model: the reactor owns the writes (the async-gRPC / asio
``async_write`` discipline). Handlers never send on the socket themselves —
they ENQUEUE serialized reply parts on the connection's outbound queue and
the queue is flushed with non-blocking scatter-gather ``sendmsg`` (one
syscall covers the length header, any number of small frames, and large
out-of-band buffers straight from their backing memory). A flush that would
block arms ``EVENT_WRITE`` and resumes when the kernel says the socket is
writable, so a stalled peer parks ITS OWN queue while every other
connection's round-trips continue unimpeded. Queues are capped
(``config.rpc_outbound_cap_bytes``, ~64 MiB): a peer that stops reading past
the cap is dropped. Every teardown — read EOF, read error, flush error,
over-cap, handler-thread failure — routes through ``_drop`` so the selector
can never retain a stale fd (fd reuse after an un-unregistered close would
kill the reactor). ``RpcClient`` multiplexes concurrent in-flight calls over
one socket with a response-reader thread, mirroring the async client-call
pattern of ``src/ray/rpc/client_call.h``; its sends are blocking
scatter-gather ``sendmsg`` on the caller's thread.
"""

from __future__ import annotations

import pickle
import selectors
import socket
import struct
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu.core.config import config
from ray_tpu.core import coremetrics as cm
from ray_tpu.util import faultinject
from ray_tpu.util import metrics as um

Addr = Tuple[str, int]

_LEN = struct.Struct(">Q")

# iovec window per sendmsg: far under Linux's UIO_MAXIOV (1024), large
# enough that a header + meta + a dozen OOB buffers go in one syscall.
_IOV_CAP = 64


def dumps(obj: Any) -> bytes:
    """Pickle with cloudpickle fallback for closures/lambdas/local classes."""
    try:
        return pickle.dumps(obj, protocol=5)
    except Exception:
        return cloudpickle.dumps(obj, protocol=5)


def loads(data) -> Any:
    return pickle.loads(data)


# Out-of-band frame layout (PEP 574): MAGIC | u32 meta_len | u32 n_bufs |
# u64 sizes[n] | meta | raw buffers. Large buffer-protocol payloads (numpy
# chunks, actor args) skip the pickle byte-copy on BOTH ends: the sender
# scatter-writes the raw buffers, the receiver reconstructs zero-copy
# views into the single recv buffer. \xff can never begin a plain pickle
# (those start with \x80 PROTO), so the magic is unambiguous.
_OOB_MAGIC = b"\xffRTB1"


def dumps_parts(obj: Any) -> list:
    """Serialize to a list of send buffers (scatter-gather). Falls back to
    one in-band pickle part for cloudpickle payloads and non-contiguous
    buffers."""
    bufs: list = []
    try:
        meta = pickle.dumps(obj, protocol=5, buffer_callback=bufs.append)
        raws = [b.raw() for b in bufs]
    except Exception:
        return [dumps(obj)]
    if not raws:
        return [meta]
    head = b"".join([_OOB_MAGIC,
                     struct.pack("<II", len(meta), len(raws)),
                     struct.pack(f"<{len(raws)}Q",
                                 *(r.nbytes for r in raws)),
                     meta])
    return [head] + raws


def loads_frame(frame) -> Any:
    view = memoryview(frame)
    if bytes(view[:len(_OOB_MAGIC)]) != _OOB_MAGIC:
        return pickle.loads(view)
    off = len(_OOB_MAGIC)
    meta_len, n = struct.unpack_from("<II", view, off)
    off += 8
    sizes = struct.unpack_from(f"<{n}Q", view, off)
    off += 8 * n
    meta = view[off:off + meta_len]
    off += meta_len
    buffers = []
    for s in sizes:
        buffers.append(view[off:off + s])
        off += s
    return pickle.loads(meta, buffers=buffers)


def _byte_view(p) -> memoryview:
    mv = p if isinstance(p, memoryview) else memoryview(p)
    if mv.ndim != 1 or mv.format != "B":
        mv = mv.cast("B")
    return mv


def _sendmsg_all(sock: socket.socket, bufs: List[memoryview]) -> None:
    """Blocking scatter-gather send of every buffer, in order. Handles
    partial sends and iovec windows; zero copies on the Python side."""
    idx, off = 0, 0
    n = len(bufs)
    while idx < n:
        window: List[memoryview] = []
        total = 0
        i, cur_off = idx, off
        while i < n and len(window) < _IOV_CAP and total < (8 << 20):
            mv = bufs[i]
            if cur_off:
                mv = mv[cur_off:]
                cur_off = 0
            window.append(mv)
            total += mv.nbytes
            i += 1
        sent = sock.sendmsg(window)
        while sent > 0:
            rem = bufs[idx].nbytes - off
            if sent >= rem:
                sent -= rem
                idx += 1
                off = 0
            else:
                off += sent
                sent = 0


def send_frame(sock: socket.socket, payload) -> None:
    """Client-side framed send: ONE scatter-gather ``sendmsg`` covers the
    length header and every payload part (header copy eliminated; large
    OOB buffers go straight from their backing memory, e.g. an mmap'd
    store chunk never lands in an intermediate bytearray)."""
    if isinstance(payload, (bytes, bytearray, memoryview)):
        payload = [payload]
    bufs: List[memoryview] = []
    total = 0
    for p in payload:
        mv = _byte_view(p)
        if mv.nbytes:
            bufs.append(mv)
            total += mv.nbytes
    _chaos_gate(sock, total)
    _sendmsg_all(sock, [memoryview(_LEN.pack(total))] + bufs)


def recv_exact(sock: socket.socket, n: int) -> memoryview:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], min(n - got, 8 << 20))
        if r == 0:
            raise ConnectionError("socket closed mid-frame")
        got += r
    return view


def recv_frame(sock: socket.socket) -> memoryview:
    header = recv_exact(sock, _LEN.size)
    (length,) = _LEN.unpack(header)
    return recv_exact(sock, length)


# Network-chaos injection seam (reference: tc-based latency/bandwidth
# chaos, tests/chaos/chaos_network_delay.yaml + chaos_network_bandwidth
# .yaml — here in-process so the multi-node-in-one-machine fixture can
# exercise slow/lossy links without root/tc). Client sends apply it as a
# blocking gate on the caller's thread; server replies apply it as
# NON-BLOCKING per-connection pacing in the reactor flush (delay and
# bandwidth push out the conn's next_send_t, drop severs the conn), so a
# throttled peer never stalls the reactor for other connections.
_chaos = {"delay_s": 0.0, "jitter_s": 0.0, "drop_prob": 0.0, "rng": None,
          "bandwidth_bps": 0.0}


def set_network_chaos(delay_ms: float = 0.0, jitter_ms: float = 0.0,
                      drop_prob: float = 0.0,
                      bandwidth_mbps: float = 0.0, seed: int = 0) -> None:
    """Inject latency/jitter/loss/bandwidth limits into every outbound RPC
    of THIS process. ``drop_prob`` drops the send by severing the
    connection (the peer sees a reset — exercising the same reconnect
    paths a flaky network does). Zero everything to disable."""
    import random as _random

    _chaos.update(delay_s=delay_ms / 1e3, jitter_s=jitter_ms / 1e3,
                  drop_prob=drop_prob,
                  bandwidth_bps=bandwidth_mbps * 125_000.0,
                  rng=_random.Random(seed))


def _chaos_gate(sock: socket.socket, nbytes: int) -> None:
    if _chaos["rng"] is None:
        return
    if _chaos["drop_prob"] and _chaos["rng"].random() < _chaos["drop_prob"]:
        try:
            sock.close()
        except OSError:
            pass
        raise OSError("chaos: connection dropped")
    delay = _chaos["delay_s"]
    if _chaos["jitter_s"]:
        delay += _chaos["rng"].uniform(0.0, _chaos["jitter_s"])
    if _chaos["bandwidth_bps"]:
        delay += nbytes / _chaos["bandwidth_bps"]
    if delay > 0:
        time.sleep(delay)


class RpcError(Exception):
    """Transport-level failure (peer died, connection refused)."""


class RpcConnectError(RpcError):
    """The peer could not be dialed at all (connect retries exhausted).
    Distinct from a mid-call transport failure so callers can tell "the
    process at this address is gone" (its state died with it — safe to
    abandon per-peer work) from "the connection hiccuped" (retry)."""


class RpcTimeout(RpcError, TimeoutError):
    """A bounded call's reply did not land within its timeout. Also a
    ``TimeoutError``, so pre-existing ``except TimeoutError`` callers
    keep working; the RpcError base lets transport-failure handlers
    treat a timed-out peer like a dead one (same recovery: re-resolve,
    retry, or raise the typed refusal)."""


class RemoteCallError(Exception):
    """The handler on the peer raised; carries the remote exception."""

    def __init__(self, cause: BaseException):
        self.cause = cause
        super().__init__(repr(cause))


# Selector-key sentinel for the reactor's self-wake socket.
_WAKE = object()


class RpcServer:
    """Reactor request/response server.

    ``handlers`` maps method name -> callable(*args, **kwargs). Handlers run
    on a thread pool (or inline on the reactor for ``inline_methods``); their
    return value (or raised exception) is shipped back to the caller. A
    request with ``id is None`` is a one-way notification. Replies are queued
    per connection and flushed by the reactor with non-blocking ``sendmsg``
    (see module docstring) — no code path ever blocks in ``send`` on the
    reactor thread.
    """

    def __init__(
        self,
        handlers: Dict[str, Callable],
        host: str = "127.0.0.1",
        port: int = 0,
        name: str = "rpc",
        max_workers: int = 64,
        inline_methods: Optional[set] = None,
        outbound_cap_bytes: Optional[int] = None,
    ):
        self._handlers = dict(handlers)
        # Methods run directly on the connection reader thread instead of the
        # shared pool. Use for quick, never-blocking handlers that must make
        # progress even when the pool is saturated with blocking calls (e.g.
        # a node's return_worker while many lease_worker calls wait). Since
        # replies are enqueued (never sent blocking), an inline handler can
        # reply to an arbitrarily slow peer without stalling the reactor.
        self._inline = set(inline_methods or ())
        self._name = name
        self._out_cap = (outbound_cap_bytes if outbound_cap_bytes is not None
                         else config.rpc_outbound_cap_bytes)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(256)
        self.addr: Addr = self._sock.getsockname()
        self._pool = ThreadPoolExecutor(max_workers=max_workers,
                                        thread_name_prefix=f"{name}-h")
        self._stopped = threading.Event()
        self._conns: list[socket.socket] = []
        self._conns_lock = threading.Lock()
        # Reactor, not thread-per-connection: ONE selector thread reads
        # every connection (a 5,000-worker fleet means 5,000 inbound
        # sockets on a node/controller — a reader thread each breaks the
        # process's thread/mmap budget long before CPU does). Inline
        # methods run on the reactor; the rest dispatch to the pool.
        self._selector = selectors.DefaultSelector()
        # The listening socket lives in the same selector (data=None
        # marks it): one thread accepts AND reads — at 5,000 workers per
        # box, every thread per process counts against kernel.pid_max.
        self._sock.setblocking(False)
        self._selector.register(self._sock, selectors.EVENT_READ, None)
        # Self-wake pipe: handler threads post selector work (arm a
        # conn's EVENT_WRITE, drop a conn) to _ops and poke the reactor.
        # Only the reactor touches the selector — stdlib selectors are
        # not thread-safe for concurrent modify/select.
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._selector.register(self._wake_r, selectors.EVENT_READ, _WAKE)
        self._ops: deque = deque()
        self._ops_lock = threading.Lock()
        # Connections with queued data deferred by chaos pacing
        # (reactor-private; see _flush).
        self._paced: List[RpcServer._Conn] = []
        # Write-path observability: plain counters on the conn state
        # (updated under st.lock, which the write path already holds)
        # and reactor-private fold-in totals — the reactor and handler
        # threads NEVER touch the metrics registry. _collect_metrics
        # publishes at snapshot time (weakly registered: the collector
        # dies with the server).
        self._conn_states: Dict[RpcServer._Conn, None] = {}
        self._m_closed_frames = 0
        self._m_closed_bytes = 0
        self._m_closed_bp = 0
        self._m_conn_drops = 0
        self._m_flush_samples: deque = deque(maxlen=512)
        self._m_deltas = um.CounterDeltas()
        um.add_collector(self._collect_metrics)
        self._reactor_thread = threading.Thread(
            target=self._reactor, name=f"{name}-reactor", daemon=True)
        self._reactor_thread.start()

    def _collect_metrics(self) -> None:
        """Snapshot-time publisher for the write-path counters (runs on
        the metrics flusher/agent thread, never the reactor)."""
        if not config.core_metrics_enabled or self._stopped.is_set():
            return
        with self._conns_lock:
            live = list(self._conn_states)
        frames, nbytes, bp = (self._m_closed_frames, self._m_closed_bytes,
                              self._m_closed_bp)
        q_bytes = 0
        q_conns = 0
        for st in live:
            # st.lock per conn: an unlocked read can land between the
            # reactor's sendmsg and its out_bytes decrement and report
            # phantom queue bytes. This is a snapshot-cadence path; the
            # reactor holds each lock only for one non-blocking flush.
            with st.lock:
                frames += st.m_frames
                nbytes += st.m_bytes
                bp += st.m_bp
                out_bytes = st.out_bytes
            if out_bytes > 0:
                q_bytes += out_bytes
                q_conns += 1
        tags = {"server": self._name}
        cm.RPC_OUT_QUEUE_BYTES.set(float(q_bytes), tags)
        cm.RPC_OUT_QUEUE_CONNS.set(float(q_conns), tags)
        self._m_deltas.inc_to(cm.RPC_TX_FRAMES, "frames", frames, tags)
        self._m_deltas.inc_to(cm.RPC_TX_BYTES, "bytes", nbytes, tags)
        self._m_deltas.inc_to(cm.RPC_BACKPRESSURE_DROPS, "bp", bp, tags)
        self._m_deltas.inc_to(cm.RPC_CONN_DROPS, "drops",
                              self._m_conn_drops, tags)
        samples = []
        while True:
            try:
                samples.append(self._m_flush_samples.popleft())
            except IndexError:
                break
        if samples:
            cm.RPC_FLUSH_S.observe_many(samples, tags)

    def register(self, method: str, fn: Callable) -> None:
        self._handlers[method] = fn

    class _Conn:
        __slots__ = ("sock", "buf", "out", "out_bytes", "lock", "writing",
                     "dead", "next_send_t", "m_frames", "m_bytes", "m_bp")

        def __init__(self, sock):
            self.sock = sock
            self.buf = bytearray()          # inbound partial frames
            self.out = deque()              # outbound memoryviews
            self.out_bytes = 0
            self.lock = threading.Lock()    # guards out/out_bytes/dead
            self.writing = False            # EVENT_WRITE armed (reactor-only)
            self.dead = False
            self.next_send_t = 0.0          # chaos pacing gate
            self.m_frames = 0               # metrics (under lock; folded
            self.m_bytes = 0                # into the server's closed
            self.m_bp = 0                   # totals by _drop)

    # ----------------------------------------------------------- accept/read

    def _accept(self) -> None:
        while True:
            try:
                conn, _ = self._sock.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.setblocking(False)
            st = RpcServer._Conn(conn)
            with self._conns_lock:
                self._conns.append(conn)
                self._conn_states[st] = None
            try:
                self._selector.register(conn, selectors.EVENT_READ, st)
            except KeyError:
                # A stale key under this fd number means some teardown
                # bypassed _drop (must not happen — but a dead entry here
                # would otherwise kill the reactor on the NEXT register).
                # Evict it and retry.
                try:
                    self._selector.unregister(conn)
                except (KeyError, OSError, ValueError):
                    pass
                try:
                    self._selector.register(conn, selectors.EVENT_READ, st)
                except (KeyError, OSError, ValueError):
                    self._drop(st)
            except (OSError, ValueError):
                self._drop(st)

    def _drop(self, st: "_Conn") -> None:
        """The single teardown path: marks the conn dead, clears its queue,
        unregisters it, closes it. Reactor-thread only (handler threads
        post a 'drop' op instead)."""
        with st.lock:
            st.dead = True
            st.out.clear()
            st.out_bytes = 0
        if st in self._paced:
            self._paced.remove(st)
        try:
            self._selector.unregister(st.sock)
        except (KeyError, OSError, ValueError):
            pass
        try:
            st.sock.close()
        except OSError:
            pass
        with self._conns_lock:
            if st.sock in self._conns:
                self._conns.remove(st.sock)
            if st in self._conn_states:
                # Fold the dead conn's counters into the server totals so
                # the collector's cumulative view never goes backwards.
                del self._conn_states[st]
                self._m_closed_frames += st.m_frames
                self._m_closed_bytes += st.m_bytes
                self._m_closed_bp += st.m_bp
                self._m_conn_drops += 1

    # ------------------------------------------------------------- wake/ops

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"\x00")
        except (BlockingIOError, OSError):
            pass  # wake buffer full => reactor is already waking

    def _post_op(self, op: str, st: "_Conn") -> None:
        with self._ops_lock:
            self._ops.append((op, st))
        self._wake()

    def _drain_ops(self) -> None:
        while True:
            with self._ops_lock:
                if not self._ops:
                    return
                op, st = self._ops.popleft()
            if op == "drop":
                self._drop(st)
            elif not st.dead:  # "arm": flush now; arm/pace as needed
                self._flush(st)

    # ---------------------------------------------------------------- reactor

    def _reactor(self) -> None:
        while not self._stopped.is_set():
            self._drain_ops()
            timeout = 0.5
            if self._paced:
                now = time.monotonic()
                due = [st for st in self._paced
                       if st.dead or st.next_send_t <= now]
                for st in due:
                    self._paced.remove(st)
                    if not st.dead:
                        self._flush(st)
                if self._paced:
                    soonest = min(st.next_send_t for st in self._paced)
                    timeout = min(timeout,
                                  max(0.001, soonest - time.monotonic()))
            try:
                events = self._selector.select(timeout=timeout)
            except OSError:
                return
            for key, mask in events:
                st = key.data
                if st is None:  # the listening socket
                    self._accept()
                    continue
                if st is _WAKE:
                    try:
                        while self._wake_r.recv(4096):
                            pass
                    except (BlockingIOError, OSError):
                        pass
                    self._drain_ops()
                    continue
                if st.dead:  # dropped earlier in this event batch
                    continue
                if mask & selectors.EVENT_WRITE:
                    self._flush(st)
                if (mask & selectors.EVENT_READ) and not st.dead:
                    self._read(st)

    def _read(self, st: "_Conn") -> None:
        try:
            data = st.sock.recv(1 << 20)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._drop(st)
            return
        if not data:
            self._drop(st)
            return
        st.buf += data
        self._pump(st)

    def _pump(self, st: "_Conn") -> None:
        """Dispatch every complete frame buffered on the connection."""
        hdr = _LEN.size
        while not st.dead:
            if len(st.buf) < hdr:
                return
            (length,) = _LEN.unpack_from(st.buf)
            if len(st.buf) < hdr + length:
                return
            frame = bytes(st.buf[hdr:hdr + length])
            del st.buf[:hdr + length]
            try:
                msg = loads_frame(memoryview(frame))
            except Exception:
                self._drop(st)
                return
            if msg.get("method") in self._inline:
                self._handle(st, msg)
            else:
                try:
                    self._pool.submit(self._handle, st, msg)
                except RuntimeError:
                    # Pool shut down while a request was in flight:
                    # server stopping, or interpreter exit (the
                    # concurrent.futures atexit hook kills all pools
                    # before daemon threads die). Drop the request.
                    self._drop(st)
                    return

    # ------------------------------------------------------------ write path

    def _handle(self, st: "_Conn", msg) -> None:
        req_id = msg.get("id")
        try:
            if config.faultinject_path:
                # Named-endpoint fault injection (chaos tests only; the
                # flag gate keeps the hot path one attribute read). A
                # delay rule here CAN stall the reactor for inline
                # methods — deliberately: that's how a test simulates a
                # wedged control plane.
                faultinject.check(
                    f"rpc.server.{self._name}.{msg.get('method')}")
            handler = self._handlers[msg["method"]]
            result = handler(*msg.get("args", ()), **msg.get("kwargs", {}))
            reply = {"id": req_id, "ok": True, "result": result}
        except faultinject.FaultDropped:
            return  # injected lost reply: the caller's timeout governs
        except BaseException as e:  # noqa: BLE001 — errors must reach the caller
            reply = {"id": req_id, "ok": False, "error": e}
        if req_id is None:
            return
        try:
            parts = dumps_parts(reply)
        except Exception as e:
            parts = [dumps({"id": req_id, "ok": False,
                            "error": RpcError(f"unpicklable reply: {e!r}")})]
        self._send_reply(st, parts)

    def _send_reply(self, st: "_Conn", parts: list) -> None:
        """Enqueue one framed reply on the connection's outbound queue and
        flush opportunistically (non-blocking). Residue is flushed by the
        reactor on EVENT_WRITE. Never raises; never blocks."""
        # Zero-length parts (e.g. the 0-byte OOB PickleBuffer an empty
        # numpy array yields) must never reach the queue: sendmsg consumes
        # 0 bytes of them, so an unfiltered one would sit at the queue
        # head forever and wedge the flush loop.
        bufs = [mv for mv in map(_byte_view, parts) if mv.nbytes]
        total = sum(mv.nbytes for mv in bufs)
        rng = _chaos["rng"]
        if rng is not None:
            if _chaos["drop_prob"] and rng.random() < _chaos["drop_prob"]:
                with st.lock:
                    st.dead = True
                self._post_op("drop", st)
                return
            delay = _chaos["delay_s"]
            if _chaos["jitter_s"]:
                delay += rng.uniform(0.0, _chaos["jitter_s"])
        else:
            delay = 0.0
        with st.lock:
            if st.dead:
                return
            if st.out_bytes + _LEN.size + total > self._out_cap:
                # Backpressure: the peer stopped reading and its queue hit
                # the cap. A partial frame may already be on the wire, so
                # the stream is torn either way — drop the conn.
                st.dead = True
                st.m_bp += 1
                status = "error"
            else:
                st.out.append(memoryview(_LEN.pack(total)))
                st.out.extend(bufs)
                st.out_bytes += _LEN.size + total
                st.m_frames += 1
                st.m_bytes += _LEN.size + total
                if delay > 0:
                    st.next_send_t = max(st.next_send_t,
                                         time.monotonic() + delay)
                status = self._flush_locked(st)
        if status == "error":
            self._post_op("drop", st)
        elif status != "drained":
            self._post_op("arm", st)

    def _flush_locked(self, st: "_Conn") -> str:
        """Send as much queued data as the socket (and chaos pacing) allows.
        Caller holds ``st.lock``. Returns 'drained' | 'blocked' | 'paced' |
        'error'; on 'error' the conn is marked dead (caller routes to
        _drop). Never blocks: the socket is non-blocking."""
        bps = _chaos["bandwidth_bps"] if _chaos["rng"] is not None else 0.0
        while st.out:
            now = time.monotonic()
            if now < st.next_send_t:
                return "paced"
            window: List[memoryview] = []
            total = 0
            limit = max(4096, int(bps * 0.05)) if bps else (8 << 20)
            for mv in st.out:
                if total + mv.nbytes > limit and window:
                    break
                if mv.nbytes > limit - total:
                    mv = mv[:limit - total]
                window.append(mv)
                total += mv.nbytes
                if len(window) >= _IOV_CAP:
                    break
            try:
                sent = st.sock.sendmsg(window)
            except (BlockingIOError, InterruptedError):
                return "blocked"
            except OSError:
                st.dead = True
                return "error"
            st.out_bytes -= sent
            if bps:
                st.next_send_t = max(st.next_send_t, now) + sent / bps
            # `sent >= head.nbytes` holds for a 0-byte head even when
            # sent == 0, so stray empty views can never pin the queue.
            while sent > 0 or (st.out and st.out[0].nbytes == 0):
                head = st.out[0]
                if sent >= head.nbytes:
                    sent -= head.nbytes
                    st.out.popleft()
                else:
                    st.out[0] = head[sent:]
                    sent = 0
        return "drained"

    def _flush(self, st: "_Conn") -> None:
        """Reactor-side flush + interest-set bookkeeping."""
        timed = config.core_metrics_enabled
        t0 = time.perf_counter() if timed else 0.0
        with st.lock:
            status = self._flush_locked(st)
        if timed:
            # Bounded ring, drained by the snapshot-time collector; cost
            # on the reactor is two clock reads and a deque append.
            self._m_flush_samples.append(time.perf_counter() - t0)
        if status == "error":
            self._drop(st)
        elif status == "drained":
            self._set_writing(st, False)
        elif status == "blocked":
            self._set_writing(st, True)
        else:  # paced: park off the selector so a writable socket doesn't spin
            self._set_writing(st, False)
            if not st.dead and st not in self._paced:
                self._paced.append(st)

    def _set_writing(self, st: "_Conn", on: bool) -> None:
        if st.writing == on or st.dead:
            return
        mask = selectors.EVENT_READ | (selectors.EVENT_WRITE if on else 0)
        try:
            self._selector.modify(st.sock, mask, st)
        except (KeyError, OSError, ValueError):
            self._drop(st)
            return
        st.writing = on

    def stop(self) -> None:
        self._stopped.set()
        self._wake()  # pop the reactor out of select() immediately
        self._reactor_thread.join(timeout=2.0)
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            for c in self._conns:
                try:
                    c.close()
                except OSError:
                    pass
        if not self._reactor_thread.is_alive():
            # Only reap the wake fds and selector once the reactor has
            # actually exited: a reactor wedged past the join timeout
            # would otherwise select() on closed — soon reused — fds.
            for s in (self._wake_r, self._wake_w):
                try:
                    s.close()
                except OSError:
                    pass
            try:
                self._selector.close()
            except (OSError, RuntimeError):
                pass
        self._pool.shutdown(wait=False)


class RpcClient:
    """Client multiplexing concurrent calls over one TCP connection."""

    def __init__(self, addr: Addr, connect_timeout: Optional[float] = None,
                 role: str = "peer"):
        self.addr = tuple(addr)
        self._role = role  # dial-metrics label: controller | peer
        self._sock = _connect(self.addr, connect_timeout, role)
        self._send_lock = threading.Lock()
        self._next_id = 0
        self._id_lock = threading.Lock()
        self._pending: Dict[int, _PendingCall] = {}
        self._pending_lock = threading.Lock()
        self._closed = False
        self._pool_evicted = False
        self._lifecycle_lock = threading.Lock()
        self._reader = threading.Thread(target=self._read_loop,
                                        args=(self._sock,),
                                        name="rpc-client-read", daemon=True)
        self._reader.start()

    def _read_loop(self, sock: socket.socket) -> None:
        try:
            while True:
                msg = loads_frame(recv_frame(sock))
                with self._pending_lock:
                    call = self._pending.pop(msg["id"], None)
                if call is not None:
                    call.complete(msg)
        except (ConnectionError, OSError):
            # Guard against a stale reader (pre-redial socket) failing the
            # fresh connection's in-flight calls. The unlocked _sock read
            # is the point: an identity probe against whatever socket is
            # current — a racing re-dial makes the comparison fail and
            # this stale reader exit silently, which is the desired
            # outcome.
            # graftlint: disable=unguarded-field-access
            if sock is self._sock:
                self._fail_all(RpcError(f"connection to {self.addr} lost"))

    def _fail_all(self, err: Exception) -> None:
        # _closed writes go through _lifecycle_lock (like close/_evict/
        # _ensure_open): an unlocked write here could interleave with
        # _ensure_open's re-dial sequence and publish a half-built
        # open-but-closed state (graftlint: unguarded-field-access).
        with self._lifecycle_lock:
            self._closed = True
        with self._pending_lock:
            pending, self._pending = self._pending, {}
        for call in pending.values():
            call.fail(err)

    def _ensure_open(self) -> None:
        # Double-checked fast path: a stale read here only costs one
        # trip into the locked re-check below, never a wrong decision.
        # graftlint: disable=unguarded-field-access
        if not self._closed:
            return
        with self._lifecycle_lock:
            if not self._closed:
                return
            if not self._pool_evicted:
                raise RpcError(f"client to {self.addr} is closed")
            # The pool reclaimed this idle connection while a caller still
            # held the handle (the get()/call() race): transparently
            # re-dial. Eviction requires no in-flight calls, so nothing is
            # lost; any stragglers were failed by the old reader. The dial
            # happens under _lifecycle_lock ON PURPOSE: it serializes
            # against _evict/close so eviction can never shut a half-built
            # fresh socket (see _evict's docstring).
            # graftlint: disable=lock-held-blocking
            self._sock = _connect(self.addr, None, self._role)
            self._pool_evicted = False
            self._closed = False
            self._reader = threading.Thread(target=self._read_loop,
                                            args=(self._sock,),
                                            name="rpc-client-read",
                                            daemon=True)
            self._reader.start()

    def call(self, method: str, *args, timeout: Optional[float] = None, **kwargs):
        if config.faultinject_path:
            # Client-side endpoint faults: error = typed failure the
            # caller handles (NOT retried by ReconnectingClient), drop =
            # torn-connection semantics (retried/reconnected).
            faultinject.check(f"rpc.client.{method}")
        self._ensure_open()
        with self._id_lock:
            self._next_id += 1
            req_id = self._next_id
        payload = dumps_parts({"id": req_id, "method": method,
                               "args": args, "kwargs": kwargs})
        for attempt in (0, 1):
            # Fresh per attempt: a failure is sticky on _PendingCall, and
            # the evicted socket's dying reader may have failed the first
            # registration via _fail_all before the retry resends.
            call = _PendingCall()
            with self._pending_lock:
                self._pending[req_id] = call
            try:
                # _send_lock held across the blocking send BY DESIGN:
                # its entire purpose is to serialize frame writes so two
                # threads can't interleave torn frames on the wire.
                # Client sends are caller-thread blocking (module
                # docstring); only the server reactor is non-blocking.
                # The unlocked _sock read is part of the protocol too: a
                # racing _evict closes it and the OSError arm below
                # re-dials and resends.
                with self._send_lock:
                    # graftlint: disable=lock-held-blocking, unguarded-field-access
                    send_frame(self._sock, payload)
                break
            except OSError as e:
                with self._pending_lock:
                    self._pending.pop(req_id, None)
                # Racy read by design: _evict flips the flag BEFORE
                # closing the socket, so a send that failed because of
                # eviction always sees it set; a stale False just
                # surfaces the send error to a caller that raced close().
                # graftlint: disable=unguarded-field-access
                if attempt == 0 and self._pool_evicted:
                    # Eviction closed the socket between our open-check
                    # and the send: re-dial and resend. Any partial frame
                    # died with the old connection, so no duplicate.
                    self._ensure_open()
                    continue
                self._fail_all(RpcError(f"send to {self.addr} failed: {e}"))
                raise RpcError(f"send to {self.addr} failed: {e}") from e
        try:
            return call.wait(timeout)
        except TimeoutError:
            with self._pending_lock:
                self._pending.pop(req_id, None)
            raise

    def notify(self, method: str, *args, **kwargs) -> None:
        """Fire-and-forget one-way message."""
        self._ensure_open()
        payload = dumps_parts({"id": None, "method": method,
                               "args": args, "kwargs": kwargs})
        for attempt in (0, 1):
            try:
                # Same frame-write serialization (and deliberate racy
                # _sock read) as call() above.
                with self._send_lock:
                    # graftlint: disable=lock-held-blocking, unguarded-field-access
                    send_frame(self._sock, payload)
                return
            except OSError as e:
                # Same deliberate racy read as call() above.
                # graftlint: disable=unguarded-field-access
                if attempt == 0 and self._pool_evicted:
                    self._ensure_open()  # send overlapped pool eviction
                    continue
                raise RpcError(f"send to {self.addr} failed: {e}") from e

    def close(self) -> None:
        """Permanent close (owner teardown, pool invalidate/close_all).
        Serialized with ``_ensure_open``'s re-dial via ``_lifecycle_lock``
        so it can never clobber a half-built fresh connection; pool
        eviction goes through ``_evict`` instead and stays re-dialable."""
        with self._lifecycle_lock:
            self._pool_evicted = False
            self._closed = True
            try:
                self._sock.close()
            except OSError:
                pass

    def _evict(self) -> None:
        """Pool-side close of an idle client a caller may still hold.
        The evicted mark and the socket close happen atomically under
        ``_lifecycle_lock``: a holder's re-dial can only run before this
        (impossible — only ``_evict`` sets ``_pool_evicted``) or after the
        OLD socket is closed, so eviction can never shut a fresh socket
        and strand the client permanently closed."""
        with self._lifecycle_lock:
            if self._closed:
                return  # already dead (connection loss or real close)
            self._pool_evicted = True
            self._closed = True
            try:
                self._sock.close()
            except OSError:
                pass


class _PendingCall:
    __slots__ = ("_event", "_msg", "_err")

    def __init__(self):
        self._event = threading.Event()
        self._msg = None
        self._err = None

    def complete(self, msg) -> None:
        self._msg = msg
        self._event.set()

    def fail(self, err: Exception) -> None:
        self._err = err
        self._event.set()

    def wait(self, timeout: Optional[float]):
        if not self._event.wait(timeout):
            raise RpcTimeout("RPC call timed out")
        if self._err is not None:
            raise self._err
        if not self._msg["ok"]:
            err = self._msg["error"]
            raise RemoteCallError(err) from err
        return self._msg["result"]


def _connect(addr: Addr, timeout: Optional[float],
             role: str = "peer") -> socket.socket:
    if config.faultinject_path:
        # Partition injection: an error/drop rule on this peer's address
        # makes every dial from this process fail — a one-way partition.
        faultinject.check(f"rpc.dial.{addr[0]}:{addr[1]}")
    retries = config.rpc_connect_retries
    instrumented = config.core_metrics_enabled
    deadline = None if timeout is None else time.monotonic() + timeout
    last_err: Optional[Exception] = None
    for _ in range(max(1, retries)):
        try:
            sock = socket.create_connection(addr, timeout=5.0)
        except OSError as e:
            # Every failed attempt counts: a dead address under active
            # redial shows up as a failure STORM in the cluster view,
            # which is exactly the reconnect-storm signature ray_tpu
            # doctor detects. Label is the peer ROLE (bounded), never
            # the address (ephemeral ports = unbounded cardinality).
            if instrumented:
                cm.RPC_DIAL_FAILURES.inc(1.0, {"role": role})
            last_err = e
            if deadline is not None and time.monotonic() > deadline:
                break
            time.sleep(0.05)
            continue
        try:
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if instrumented:
                cm.RPC_DIALS.inc(1.0, {"role": role})
            return sock
        except OSError as e:
            # Post-connect setup failing must not orphan the connected
            # fd — one leaked socket per retry adds up under a flapping
            # peer (graftlint: resource-leak-path).
            sock.close()
            last_err = e
            if deadline is not None and time.monotonic() > deadline:
                break
            time.sleep(0.05)
    raise RpcConnectError(f"could not connect to {addr}: {last_err}")


class ReconnectingClient:
    """Controller-facing client that survives peer restarts.

    The reference's GCS client retries RPCs with backoff while the GCS is
    down and reconnects when it returns (``gcs_rpc_client.h`` retry loop);
    this is that behavior for the framed-pickle transport: on a transport
    error the socket is re-established and the call retried until
    ``retry_window_s`` elapses. Only use against the controller — its
    handlers are idempotent by design (re-register, kv_put, heartbeat,
    create_placement_group 2PC)."""

    def __init__(self, addr: Addr, retry_window_s: float = 10.0,
                 role: str = "controller"):
        self.addr = tuple(addr)
        self._window = retry_window_s
        self._role = role
        self._client: Optional[RpcClient] = None
        self._lock = threading.Lock()
        self._closed = False

    @staticmethod
    def _backoff_s(attempt: int) -> float:
        """Retry pause for the ``attempt``-th consecutive transport
        failure: base * 2^attempt, capped, with +/-50% jitter. The first
        retry stays FAST (base default 50 ms — a controller blip heals
        within one beat) while a dead controller decays to a capped
        trickle instead of the flat 0.2 s loop every client in the
        fleet used to synchronize on — that tight loop IS the
        reconnect-storm signature ``ray_tpu doctor`` flags, and the
        clients were its biggest in-tree source."""
        import random

        base = config.rpc_reconnect_backoff_base_ms / 1e3
        cap = config.rpc_reconnect_backoff_cap_ms / 1e3
        return min(cap, base * (2 ** attempt)) * (0.5 + random.random())

    def _get(self) -> RpcClient:
        with self._lock:
            if self._closed:
                raise RpcError(f"client to {self.addr} is closed")
            client = self._client
        if client is not None and not client._closed:
            return client
        # Dial OUTSIDE the lock: a peer that is down costs a connect
        # retry loop (seconds), and holding _lock across it would wedge
        # every concurrent call/notify/close on this handle behind one
        # stuck re-dial (graftlint: lock-held-blocking). Concurrent
        # re-dials are possible and cheap; first one in wins.
        fresh = RpcClient(self.addr, role=self._role)
        with self._lock:
            if self._closed:
                winner = None
            elif self._client is None or self._client._closed:
                self._client = fresh
                winner = fresh
            else:
                winner = self._client
        if winner is not fresh:
            fresh.close()
        if winner is None:
            raise RpcError(f"client to {self.addr} is closed")
        return winner

    def call(self, method: str, *args, timeout: Optional[float] = None,
             **kwargs):
        deadline = time.monotonic() + self._window
        attempt = 0
        while True:
            try:
                return self._get().call(method, *args, timeout=timeout,
                                        **kwargs)
            except TimeoutError:
                # A per-call timeout on a healthy connection is the
                # caller's latency bound, not a transport failure —
                # resending would both break the bound and duplicate the
                # request (TimeoutError subclasses OSError since 3.10, so
                # this arm must precede the transport arm).
                raise
            except (RpcError, ConnectionError, OSError):
                # Unlocked read: the worst a stale value costs is one
                # extra jittered retry against a just-closed handle, and
                # _get() re-checks _closed under _lock before dialing.
                # graftlint: disable=unguarded-field-access
                if self._closed or time.monotonic() > deadline:
                    raise
                if config.core_metrics_enabled:
                    cm.RPC_RECONNECT_RETRIES.inc(1.0, {"role": self._role})
                # Jittered exponential backoff between re-dials: fast
                # first retry, capped decay against a dead peer, and
                # the jitter de-synchronizes a fleet of clients that
                # all lost the same controller at the same instant.
                time.sleep(self._backoff_s(attempt))
                attempt += 1

    def notify(self, method: str, *args, **kwargs) -> None:
        """Best-effort one-way send (no retry: notifications are periodic)."""
        self._get().notify(method, *args, **kwargs)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._client is not None:
                self._client.close()


class ClientPool:
    """Caches one RpcClient per address; thread-safe, LRU-capped.

    Mirrors the reference's per-address gRPC client caching in the core
    worker (``core_worker_client_pool.h``, incl. its idle-connection
    reclaim). The cap matters at actor-surge scale: every cached client
    owns a reader THREAD, and a driver talking to thousands of actor workers
    would otherwise hold 5,000 threads/connections — past
    vm.max_map_count that breaks thread creation process-wide. Only
    clients with no in-flight calls are evicted, and an evicted client a
    caller still holds re-dials transparently on its next call (the pool
    marks it ``_pool_evicted`` — closing the get()/call() preemption race
    where eviction used to fail a healthy caller).
    """

    def __init__(self, max_clients: int = 1024):
        from collections import OrderedDict

        self._clients: "OrderedDict[Addr, RpcClient]" = OrderedDict()
        self._max = max_clients
        self._lock = threading.Lock()

    def get(self, addr: Addr) -> RpcClient:
        import time as _time

        addr = tuple(addr)
        now = _time.monotonic()
        with self._lock:
            client = self._clients.get(addr)
            if client is not None and not client._closed:
                self._clients.move_to_end(addr)
                client._last_handout = now
                return client
        # Dial OUTSIDE the pool lock. The connect path retries with
        # sleeps for seconds when the peer is down; under _lock that
        # head-of-line-blocked every get() for every OTHER (healthy)
        # address in the process — on the serve path, one dead replica
        # wedged the whole router (graftlint: lock-held-blocking).
        # Concurrent gets for the same addr may each dial; the first to
        # re-check under the lock wins and the rest close their socket.
        fresh = RpcClient(addr)
        evicted: List[RpcClient] = []
        now = _time.monotonic()
        with self._lock:
            client = self._clients.get(addr)
            if client is not None and not client._closed:
                self._clients.move_to_end(addr)
                client._last_handout = now
            else:
                client = fresh
                client._last_handout = now
                self._clients[addr] = client
                if len(self._clients) > self._max:
                    for key in list(self._clients):
                        if len(self._clients) <= self._max:
                            break
                        if key == addr:
                            continue
                        cand = self._clients[key]
                        # Evict only clients that are idle AND haven't
                        # been handed out recently: a thread that just
                        # got this client may not have registered its
                        # call yet, and a point-in-time _pending check
                        # alone would close the connection under it.
                        if (not cand._pending
                                and now - getattr(cand, "_last_handout",
                                                  0.0) > 5.0):
                            del self._clients[key]
                            evicted.append(cand)
        if client is not fresh:
            fresh.close()  # lost the insert race; drop the spare socket
        for c in evicted:
            c._evict()  # mark+close atomically; holders re-dial
        return client

    def invalidate(self, addr: Addr) -> None:
        with self._lock:
            client = self._clients.pop(tuple(addr), None)
        if client is not None:
            client.close()

    def close_all(self) -> None:
        with self._lock:
            clients, self._clients = list(self._clients.values()), {}
        for c in clients:
            c.close()
