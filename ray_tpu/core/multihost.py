"""Multi-host runtime: gang-scheduled host groups over the topology view.

Three subsystems independently stopped at the same wall — multi-host
GSPMD replicas (serve), multi-host RL learners, and MPMD training all
need "one process per host of a slice joined via ``jax.distributed``".
This module builds that substrate ONCE:

* :class:`GroupRegistry` — controller-side group state (the ``mh_*``
  RPC surface): group registration with a **monotonic group epoch**
  (every restart / re-election bumps it; stale-epoch writes, beats and
  barrier entries are rejected, so a deposed coordinator or a zombie
  member self-fences instead of corrupting the new gang), a
  **rendezvous barrier** (members post a payload and park until the
  whole gang arrives — the carrier for the program-hash check), a
  small per-group fenced KV (election results), and membership
  heartbeats.
* :class:`HostGroup` — the driver-side gang primitive: reserves an
  ICI-contiguous sub-slice from the topology view (**all-or-nothing**:
  a refusal feeds the autoscaler's pending demand and no member ever
  spawns), gang-spawns one :class:`HostWorker` actor per host with
  **aligned device visibility** (each member's context carries
  ``coordinator_address`` / ``process_id`` / ``num_processes`` and a
  disjoint local chip mask covering the sub-slice), elects a
  coordinator (lowest live member index; the election result is a
  fenced group-KV write), and monitors the gang: **one member dying
  kills and reconciles the whole group as a unit** — the sub-slice is
  released exactly once, never half-alive meshes — and a restart
  budget re-forms the gang under a bumped epoch (coordinator death is
  the same flow with a fresh election).
* **Program-hash barrier** — :func:`enter_program_barrier` runs a
  barrier'd fingerprint exchange BEFORE any collective: every member
  posts its trace/program fingerprint, and a mismatch raises the typed
  :class:`ProgramHashMismatch` on every member instead of the classic
  multi-host hang (ranks tracing different programs deadlock inside
  the collective, where nothing times out).
* :func:`form_jax_runtime` / :func:`join_jax_gang` — the ONE
  ``jax.distributed`` bootstrap path (train worker groups, tune trial
  gangs and host groups all route through it): the gang registers,
  every member enters the bootstrap-fingerprint barrier (misaligned
  ``num_processes``/platform/device-count is a typed refusal — a wrong
  ``num_processes`` otherwise hangs ``jax.distributed.initialize``
  itself), then joins the coordinator.

The CPU box cannot run multiprocess collectives (jaxlib 0.4.37), so
the testable contract is everything AROUND the collective: gang
spawn/teardown, death reconciliation, coordinator failover, epoch
fencing, hash-mismatch refusal, and single-process virtual-mesh parity
(a 1-host group is bit-identical to calling the engine directly).
``tests/test_multihost.py`` keeps the real-collective path for real
rigs.

Fault-injection sites: ``multihost.barrier.<group>.<member>`` (member-
side barrier entry) and ``multihost.member.<group>.<member>.beat``
(member heartbeat loop — a ``die`` rule SIGKILLs exactly that host's
worker process).
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.errors import RayTpuError
from ray_tpu.util import faultinject, flightrec, tracing
from ray_tpu.util.ratelimit import log_every

logger = logging.getLogger(__name__)


def _gang_span(name: str, **attrs):
    """A gang-lifecycle tracing span (formation, election, barrier
    entry, reconcile), gated on the train-plane tracing knob: these are
    control-plane events at human cadence, so the span cost is noise,
    but the knob keeps the off switch symmetric with the pipeline
    spans. Returns a context manager."""
    from contextlib import nullcontext

    from ray_tpu.core.config import config

    if not config.pipe_trace_spans:
        return nullcontext()
    return tracing.trace(name, **attrs)


class MultihostError(RayTpuError):
    """Base for host-group failures."""


class GangPlacementError(MultihostError):
    """All-or-nothing placement refusal: no single slice can host the
    gang contiguously (the refusal feeds the autoscaler's pending
    demand), or a member failed to spawn. Nothing is left half-alive:
    the sub-slice is released and no member survives."""


class ProgramHashMismatch(MultihostError):
    """Members' program fingerprints diverge at a pre-collective
    barrier: the typed refusal that replaces the classic multi-host
    hang (mismatched traces deadlock inside the collective)."""


class GroupEpochFenced(MultihostError):
    """This member/coordinator belongs to a deposed group epoch: a
    newer incarnation exists, so the zombie must stop touching group
    state (writes rejected, barrier entries refused)."""


class BarrierTimeout(MultihostError):
    """A gang barrier timed out with members absent — the hang made
    VISIBLE (the absent members are named; see ``ray_tpu doctor``'s
    gang-hang signature)."""


def _controller_client():
    """This process's controller RPC client (wrap it in a
    ControllerStub AT the call site — the rpc-contract linter reads
    literal ``ControllerStub(...)`` receivers as endpoint uses)."""
    from ray_tpu.core.runtime import get_core_worker

    return get_core_worker().controller


def member_name(rank: int) -> str:
    """The registry-wide member naming convention: host ``rank`` of a
    group is ``host-<rank>`` (the registry derives the expected member
    set of a barrier from ``num_hosts`` through this)."""
    return f"host-{rank}"


# =====================================================================
# Controller side: the group registry (mh_* RPC surface)
# =====================================================================


class _Barrier:
    __slots__ = ("payloads", "done")

    def __init__(self):
        self.payloads: Dict[str, Any] = {}
        self.done = False


class _GroupRecord:
    def __init__(self, group_id: str, num_hosts: int,
                 reservation_id: Optional[str], owner: str):
        self.group_id = group_id
        self.num_hosts = num_hosts
        self.reservation_id = reservation_id
        self.owner = owner
        self.epoch = 1
        # member -> {"last_beat": monotonic, "epoch": int}
        self.members: Dict[str, Dict[str, Any]] = {}
        # pending (incomplete) barriers by name; completed barriers are
        # popped — waiters hold the _Barrier object reference.
        self.barriers: Dict[str, _Barrier] = {}
        # fenced rendezvous KV (election results, bootstrap metadata).
        self.kv: Dict[str, Any] = {}

    def expected_members(self) -> List[str]:
        return [member_name(i) for i in range(self.num_hosts)]


class GroupRegistry:
    """Controller-side host-group state. All handlers run on the
    controller's RPC pool threads; ``barrier`` parks its thread on the
    condition (bounded waits) exactly like the pubsub long-polls."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._groups: Dict[str, _GroupRecord] = {}
        from ray_tpu.util import metrics as um

        um.add_collector(self._collect)

    # ------------------------------------------------------- handlers

    def register_group(self, group_id: str, num_hosts: int,
                       reservation_id: Optional[str] = None,
                       owner: str = "") -> Dict[str, Any]:
        """Create a group, or RE-register an existing id — which is the
        restart/re-election path: the epoch bumps (fencing every member
        and write of the previous incarnation), membership and pending
        barriers reset, and parked waiters wake to a stale-epoch
        refusal."""
        with self._cond:
            rec = self._groups.get(group_id)
            if rec is None:
                rec = _GroupRecord(group_id, int(num_hosts),
                                   reservation_id, owner)
                self._groups[group_id] = rec
            else:
                rec.epoch += 1
                rec.num_hosts = int(num_hosts)
                rec.reservation_id = reservation_id
                rec.members.clear()
                rec.barriers.clear()
                rec.kv.clear()
                self._cond.notify_all()
            # graftlint: disable=metrics-label-cardinality (gang ids bounded by live gangs; bounded ring)
            flightrec.record("gang.register", group=group_id,
                             epoch=rec.epoch, hosts=rec.num_hosts)
            return {"epoch": rec.epoch}

    def drop_group(self, group_id: str) -> bool:
        """Unregister (idempotent). Parked barrier waiters wake and
        return a refusal; the group's barrier-entered gauges flatten to
        zero so a dropped group can never read as a hang."""
        with self._cond:
            rec = self._groups.pop(group_id, None)
            self._cond.notify_all()
        if rec is not None:
            # graftlint: disable=metrics-label-cardinality (gang ids bounded by live gangs; bounded ring)
            flightrec.record("gang.drop", group=group_id,
                             epoch=rec.epoch)
            self._zero_entered(rec)
        return rec is not None

    def member_beat(self, group_id: str, member: str,
                    epoch: int) -> Dict[str, Any]:
        """Membership heartbeat. ``fenced=True`` tells the member its
        epoch is deposed (or its group gone) — the self-fence signal a
        zombie obeys by refusing all further group operations."""
        with self._lock:
            rec = self._groups.get(group_id)
            if rec is None:
                return {"known": False, "fenced": True, "epoch": 0}
            if epoch < rec.epoch:
                # graftlint: disable=metrics-label-cardinality (gang ids bounded by live gangs; bounded ring)
                flightrec.record("gang.beat.fenced", group=group_id,
                                 member=member, epoch=epoch,
                                 current=rec.epoch)
                return {"known": True, "fenced": True,
                        "epoch": rec.epoch}
            rec.members[member] = {"last_beat": time.monotonic(),
                                   "epoch": epoch}
            return {"known": True, "fenced": False, "epoch": rec.epoch}

    def barrier(self, group_id: str, name: str, member: str, epoch: int,
                payload: Any = None,
                timeout_s: float = 30.0) -> Dict[str, Any]:
        """Rendezvous: record ``member``'s arrival (with its payload —
        the program fingerprint) and park until every expected member
        of the CURRENT epoch arrives. Completion hands every waiter the
        full payload map (each member compares client-side — the
        mismatch refusal must raise on every rank, not just one). A
        timeout names the absent members instead of hanging."""
        deadline = time.monotonic() + max(0.0, min(float(timeout_s),
                                                   600.0))
        t0 = time.monotonic()
        with self._cond:
            rec = self._groups.get(group_id)
            if rec is None:
                return {"ok": False, "reason": "unknown_group"}
            if epoch < rec.epoch:
                return {"ok": False, "reason": "stale_epoch",
                        "epoch": rec.epoch}
            bar = rec.barriers.get(name)
            if bar is None:
                bar = _Barrier()
                rec.barriers[name] = bar
            bar.payloads[member] = payload
            if len(bar.payloads) >= rec.num_hosts:
                bar.done = True
                # Archive: waiters keep the object; the next barrier
                # under this name starts fresh.
                rec.barriers.pop(name, None)
                # graftlint: disable=metrics-label-cardinality (gang ids bounded by live gangs; bounded ring)
                flightrec.record("gang.barrier.done", group=group_id,
                                 barrier=name, epoch=epoch,
                                 hosts=rec.num_hosts)
                self._cond.notify_all()
            while not bar.done:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                cur = self._groups.get(group_id)
                if cur is not rec:
                    return {"ok": False, "reason": "group_dropped"}
                if rec.epoch > epoch:
                    return {"ok": False, "reason": "stale_epoch",
                            "epoch": rec.epoch}
                self._cond.wait(timeout=min(remaining, 0.25))
            if bar.done:
                result = {"ok": True,
                          "payloads": dict(bar.payloads)}
            else:
                arrived = sorted(bar.payloads)
                absent = sorted(set(rec.expected_members())
                                - set(arrived))
                # graftlint: disable=metrics-label-cardinality (gang ids bounded by live gangs; bounded ring)
                flightrec.record("gang.barrier.timeout", group=group_id,
                                 barrier=name, epoch=epoch,
                                 absent=",".join(absent))
                result = {"ok": False, "reason": "timeout",
                          "arrived": arrived, "absent": absent}
        self._observe_wait(time.monotonic() - t0)
        return result

    def group_put(self, group_id: str, key: str, value: Any,
                  epoch: int) -> Dict[str, Any]:
        """Fenced rendezvous-KV write (election results live here): a
        writer whose epoch is deposed gets ``stale_epoch`` back and
        must self-fence — the PR 12 ``kv_put_fenced`` idiom at group
        granularity."""
        with self._lock:
            rec = self._groups.get(group_id)
            if rec is None:
                return {"ok": False, "reason": "unknown_group"}
            if epoch < rec.epoch:
                return {"ok": False, "reason": "stale_epoch",
                        "epoch": rec.epoch}
            rec.kv[key] = value
            return {"ok": True, "epoch": rec.epoch}

    def group_get(self, group_id: str, key: str) -> Any:
        with self._lock:
            rec = self._groups.get(group_id)
            return None if rec is None else rec.kv.get(key)

    def group_state(self, group_id: Optional[str] = None
                    ) -> Dict[str, Any]:
        """Operator/test view of every group: epoch, membership with
        beat ages, pending barriers with who arrived / who is absent."""
        now = time.monotonic()

        def summary(rec: _GroupRecord) -> Dict[str, Any]:
            return {
                "group_id": rec.group_id,
                "num_hosts": rec.num_hosts,
                "epoch": rec.epoch,
                "owner": rec.owner,
                "reservation_id": rec.reservation_id,
                "members": {
                    m: {"epoch": info["epoch"],
                        "beat_age_s": round(now - info["last_beat"], 3)}
                    for m, info in rec.members.items()},
                "barriers": {
                    bname: {"arrived": sorted(bar.payloads),
                            "absent": sorted(
                                set(rec.expected_members())
                                - set(bar.payloads))}
                    for bname, bar in rec.barriers.items()},
                "kv_keys": sorted(rec.kv),
            }

        with self._lock:
            if group_id is not None:
                rec = self._groups.get(group_id)
                return summary(rec) if rec is not None else None
            return {g: summary(rec) for g, rec in self._groups.items()}

    # -------------------------------------------------------- metrics

    def _observe_wait(self, waited_s: float) -> None:
        from ray_tpu.core.config import config

        if not config.core_metrics_enabled:
            return
        from ray_tpu.core import coremetrics as cm

        cm.MH_BARRIER_WAIT_S.observe(waited_s)

    def _zero_entered(self, rec: _GroupRecord) -> None:
        """Flatten a dropped group's barrier-entered gauges: divergence
        is the doctor's gang-hang signal, and a dead group must read as
        uniform, not wedged."""
        from ray_tpu.core.config import config

        if not config.core_metrics_enabled:
            return
        from ray_tpu.core import coremetrics as cm

        for m in rec.expected_members():
            # Gang ids and member names are bounded by LIVE groups (a
            # handful per cluster, zeroed on drop), not request volume;
            # the snapshot series cap bounds any tail.
            # graftlint: disable=metrics-label-cardinality
            cm.MH_BARRIER_ENTERED.set(0.0, tags={"group": rec.group_id,
                                                 "member": m})

    def _collect(self) -> None:
        """Snapshot-time collector (util.metrics.add_collector): group
        count, per-member epochs, and the barrier-entered split the
        doctor's gang-hang signature reads (1 = arrived at a pending
        barrier, 0 = the gang is waiting on this member — uniform zero
        when nothing is pending)."""
        from ray_tpu.core.config import config

        if not config.core_metrics_enabled:
            return
        rows: List[Tuple[str, str, float, float]] = []
        with self._lock:
            n = len(self._groups)
            for rec in self._groups.values():
                arrived = set()
                for bar in rec.barriers.values():
                    arrived.update(bar.payloads)
                pending = bool(rec.barriers)
                for m in rec.expected_members():
                    ep = float(rec.members.get(m, {}).get("epoch", 0))
                    entered = 1.0 if (pending and m in arrived) else 0.0
                    rows.append((rec.group_id, m, ep, entered))
        from ray_tpu.core import coremetrics as cm

        cm.MH_GROUPS.set(float(n))
        for g, m, ep, entered in rows:
            # See _zero_entered for the cardinality justification.
            cm.MH_MEMBER_EPOCH.set(ep, tags={"group": g, "member": m})
            cm.MH_BARRIER_ENTERED.set(entered,
                                      tags={"group": g, "member": m})


# =====================================================================
# Member side: barrier entry, program fingerprints, jax gang join
# =====================================================================


def program_fingerprint(fn=None, args: tuple = (), *,
                        text: Optional[str] = None) -> str:
    """A stable fingerprint of the program a member is about to run:
    ``text`` hashes verbatim; otherwise the function is traced with
    ``jax.make_jaxpr`` and the jaxpr text is hashed — two members that
    would compile different collectives get different fingerprints."""
    import hashlib

    if text is None:
        import jax

        text = str(jax.make_jaxpr(fn)(*args))
    return hashlib.sha256(text.encode()).hexdigest()[:16]


def enter_barrier(group_id: str, member: str, epoch: int, name: str,
                  payload: Any = None,
                  timeout_s: Optional[float] = None) -> Dict[str, Any]:
    """Enter the named group barrier from a member process; returns the
    full member->payload map once the whole gang arrived. Raises the
    typed refusals (:class:`GroupEpochFenced`, :class:`BarrierTimeout`)
    instead of hanging."""
    from ray_tpu.core.config import config

    if timeout_s is None:
        timeout_s = config.mh_barrier_timeout_s
    if config.faultinject_path:
        faultinject.check(f"multihost.barrier.{group_id}.{member}")
    from ray_tpu.core.rpc_stubs import ControllerStub

    # graftlint: disable=metrics-label-cardinality (gang ids bounded by live gangs; bounded ring)
    flightrec.record("gang.barrier.enter", group=group_id, member=member,
                     barrier=name, epoch=epoch)
    # The span duration IS this member's rendezvous wait — the
    # per-member bar `ray_tpu timeline --train` renders for a barrier.
    with _gang_span(f"gang:barrier:{name}", group=group_id,
                    member=member, epoch=epoch):
        reply = ControllerStub(_controller_client()).mh_barrier(
            group_id, name, member, epoch, payload, timeout_s,
            timeout=timeout_s + 30.0)
    if reply.get("ok"):
        return reply["payloads"]
    reason = reply.get("reason")
    # graftlint: disable=metrics-label-cardinality (gang ids bounded by live gangs; bounded ring)
    flightrec.record("gang.barrier.refused", group=group_id,
                     member=member, barrier=name, epoch=epoch,
                     reason=str(reason))
    if reason == "stale_epoch":
        raise GroupEpochFenced(
            f"member {member} of group {group_id} entered barrier "
            f"{name!r} with deposed epoch {epoch} (current: "
            f"{reply.get('epoch')}) — a newer gang incarnation exists")
    if reason == "timeout":
        raise BarrierTimeout(
            f"barrier {name!r} of group {group_id}: member(s) "
            f"{reply.get('absent')} never arrived within "
            f"{timeout_s:.0f}s (arrived: {reply.get('arrived')})")
    raise MultihostError(
        f"barrier {name!r} of group {group_id} refused: {reply!r}")


def enter_program_barrier(group_id: str, member: str, epoch: int,
                          name: str, fingerprint: str,
                          timeout_s: Optional[float] = None
                          ) -> Dict[str, Any]:
    """The pre-collective program-hash check: exchange fingerprints
    through the group barrier and raise :class:`ProgramHashMismatch`
    on EVERY member when they diverge — a typed refusal where the
    collective would have hung."""
    payloads = enter_barrier(group_id, member, epoch, name,
                             payload=fingerprint, timeout_s=timeout_s)
    if len(set(payloads.values())) > 1:
        raise ProgramHashMismatch(
            f"program fingerprints diverge across group {group_id} at "
            f"barrier {name!r}: {payloads} — refusing to run the "
            f"collective (mismatched traces are the classic multi-host "
            f"hang)")
    return payloads


def join_jax_gang(group_id: str, member: str, epoch: int,
                  coordinator_address: str, num_processes: int,
                  process_id: int, platform: Optional[str] = None,
                  local_device_count: Optional[int] = None,
                  timeout_s: Optional[float] = None) -> int:
    """The ONE member-side ``jax.distributed`` join path (train worker
    gangs, tune trial gangs and host groups all call this): barrier'd
    bootstrap-fingerprint check FIRST — a member with a different
    ``num_processes``/platform/device-count raises the typed mismatch
    before ``jax.distributed.initialize``, which would otherwise hang
    waiting for processes that are never coming — then the actual
    join. Returns the global device count."""
    from ray_tpu.train import jax_backend

    fp = program_fingerprint(text=(
        f"jax.distributed|{coordinator_address}|{num_processes}|"
        f"{platform}|{local_device_count}"))
    enter_program_barrier(group_id, member, epoch, "jax-bootstrap", fp,
                          timeout_s=timeout_s)
    return jax_backend.init_process(coordinator_address, num_processes,
                                    process_id, platform,
                                    local_device_count)


# =====================================================================
# Driver side: gang registration + the jax runtime over any actor gang
# =====================================================================


def register_gang(num_members: int, *, group_id: Optional[str] = None,
                  reservation_id: Optional[str] = None,
                  owner: str = "") -> Tuple[str, int]:
    """Register a host group with the controller; returns
    ``(group_id, epoch)``. Re-registering an existing id bumps the
    epoch (restart/re-election fencing)."""
    from ray_tpu.core.config import config
    from ray_tpu.core.rpc_stubs import ControllerStub

    gid = group_id or f"gang-{uuid.uuid4().hex[:8]}"
    reg = ControllerStub(_controller_client()).mh_register_group(
        gid, num_members, reservation_id, owner,
        timeout=config.ctrl_call_timeout_s)
    return gid, reg["epoch"]


def drop_gang(group_id: str) -> bool:
    """Unregister a group (idempotent, best-effort: a head blip here
    only leaves a record the next re-registration recycles)."""
    from ray_tpu.core.config import config
    from ray_tpu.core.rpc_stubs import ControllerStub

    try:
        return ControllerStub(_controller_client()).mh_drop_group(
            group_id, timeout=config.ctrl_call_timeout_s)
    except Exception:
        log_every("multihost.drop_gang", 10.0, logger,
                  "dropping group %s failed", group_id, exc_info=True)
        return False


def registry_state(group_id: Optional[str] = None) -> Dict[str, Any]:
    """The controller's view of registered groups (``mh_group_state``)."""
    from ray_tpu.core.config import config
    from ray_tpu.core.rpc_stubs import ControllerStub

    return ControllerStub(_controller_client()).mh_group_state(
        group_id, timeout=config.ctrl_call_timeout_s)


def form_jax_runtime(actors: List[Any], jax_config, *, group_id: str,
                     epoch: int) -> str:
    """Form ONE global jax.distributed runtime across a gang of actors
    (anything exposing ``reserve_coordinator`` and
    ``join_gang_runtime`` remote methods — TrainWorker and HostWorker
    both do): the lowest-ranked member hosts the coordinator, every
    member enters the bootstrap-fingerprint barrier, then joins with
    its process index. Returns the coordinator address."""
    import ray_tpu

    coordinator = ray_tpu.get(
        actors[0].reserve_coordinator.remote(jax_config.coordinator_port),
        timeout=60.0)
    refs = [
        a.join_gang_runtime.remote(
            group_id, epoch, member_name(rank), coordinator,
            len(actors), rank, jax_config.platform,
            jax_config.local_device_count)
        for rank, a in enumerate(actors)
    ]
    counts = ray_tpu.get(refs, timeout=120.0)
    if len(set(counts)) != 1:
        raise MultihostError(
            f"inconsistent global device counts across the gang: "
            f"{counts}")
    return coordinator


def leave_jax_runtime(actors: List[Any], group_id: Optional[str] = None,
                      timeout: float = 20.0) -> None:
    """Cooperative gang teardown: every member enters the
    jax.distributed shutdown barrier concurrently (the coordination
    service outlives every client by construction), bounded by one
    shared deadline; then the group record drops."""
    import ray_tpu

    refs = [a.shutdown_jax.remote(10.0) for a in actors]
    try:
        ray_tpu.wait(refs, num_returns=len(refs), timeout=timeout)
    except Exception:  # graftlint: disable=swallowed-exception (best-effort distributed-jax leave at teardown)
        pass
    if group_id is not None:
        drop_gang(group_id)


# =====================================================================
# The gang member actor
# =====================================================================


class MemberRuntime:
    """What a user function run via :meth:`HostWorker.run` receives:
    the member's aligned context plus the group primitives (barrier,
    program-hash check, fencing state)."""

    def __init__(self, worker: "HostWorker"):
        self._worker = worker

    @property
    def ctx(self) -> Dict[str, Any]:
        return self._worker.member_info()

    @property
    def process_id(self) -> int:
        return int(self.ctx["process_id"])

    @property
    def num_processes(self) -> int:
        return int(self.ctx["num_processes"])

    @property
    def coordinator_address(self) -> Optional[str]:
        return self.ctx.get("coordinator_address")

    def barrier(self, name: str, payload: Any = None,
                timeout_s: Optional[float] = None) -> Dict[str, Any]:
        return self._worker.barrier(name, payload, timeout_s)

    def check_program(self, name: str, fn=None, args: tuple = (), *,
                      fingerprint: Optional[str] = None,
                      timeout_s: Optional[float] = None
                      ) -> Dict[str, Any]:
        if fingerprint is None:
            fingerprint = program_fingerprint(fn, args)
        return self._worker.program_barrier(name, fingerprint,
                                            timeout_s)


class HostWorker:
    """One gang member: an actor pinned to one host of the reserved
    sub-slice, holding the member's aligned context (process index,
    group size, coordinator, local chip mask) and the group runtime
    (heartbeat thread, epoch fencing, barrier entry, the jax join).
    User payloads run through :meth:`run`."""

    def __init__(self, ctx: Dict[str, Any]):
        self._lock = threading.Lock()
        self._ctx = dict(ctx)
        self._fenced = False
        self._stop = threading.Event()
        flightrec.record("gang.member.up", group=ctx.get("group_id", ""),
                         member=ctx.get("member", ""),
                         epoch=int(ctx.get("epoch", 0)))
        self._beat = threading.Thread(target=self._beat_loop,
                                      name="mh-member-beat", daemon=True)
        self._beat.start()

    # ----------------------------------------------------- heartbeat

    def _beat_loop(self) -> None:
        from ray_tpu.core.config import config

        period = config.mh_member_beat_period_s
        while not self._stop.wait(period):
            with self._lock:
                if self._fenced:
                    return
                gid = self._ctx["group_id"]
                member = self._ctx["member"]
                epoch = self._ctx["epoch"]
            try:
                # Inside the guard: an injected error/drop here is a
                # failed beat (logged, retried), not a dead beat
                # thread; a `die` rule still SIGKILLs regardless.
                if config.faultinject_path:
                    faultinject.check(
                        f"multihost.member.{gid}.{member}.beat")
                from ray_tpu.core.rpc_stubs import ControllerStub

                reply = ControllerStub(
                    _controller_client()).mh_member_beat(
                        gid, member, epoch, timeout=5.0)
            except Exception:
                # Head blip: liveness is judged by the group monitor's
                # pings, not by this beat — keep trying.
                log_every("multihost.member_beat", 10.0, logger,
                          "member beat failed", exc_info=True)
                continue
            if reply.get("fenced"):
                # Zombie: a newer group epoch exists (the gang restarted
                # without us). Stop touching group state forever.
                flightrec.record("gang.fenced", group=gid, member=member,
                                 epoch=epoch)
                with self._lock:
                    self._fenced = True
                return

    def _guard(self) -> Tuple[str, str, int]:
        with self._lock:
            if self._fenced:
                raise GroupEpochFenced(
                    f"member {self._ctx['member']} of group "
                    f"{self._ctx['group_id']} is fenced (deposed epoch "
                    f"{self._ctx['epoch']})")
            return (self._ctx["group_id"], self._ctx["member"],
                    self._ctx["epoch"])

    # ------------------------------------------------------- surface

    def ping(self) -> str:
        return "pong"

    def member_info(self) -> Dict[str, Any]:
        import os

        with self._lock:
            return {**self._ctx, "fenced": self._fenced,
                    "pid": os.getpid()}

    def fenced(self) -> bool:
        with self._lock:
            return self._fenced

    def configure(self, coordinator_address: str, coordinator: str,
                  epoch: int) -> bool:
        """The election result pushed to every member: who coordinates
        and at which address (aligned visibility — every member holds
        the same values)."""
        with self._lock:
            self._ctx["coordinator_address"] = coordinator_address
            self._ctx["coordinator"] = coordinator
            self._ctx["epoch"] = max(self._ctx["epoch"], int(epoch))
        return True

    def barrier(self, name: str, payload: Any = None,
                timeout_s: Optional[float] = None) -> Dict[str, Any]:
        gid, member, epoch = self._guard()
        return enter_barrier(gid, member, epoch, name, payload,
                             timeout_s)

    def program_barrier(self, name: str, fingerprint: str,
                        timeout_s: Optional[float] = None
                        ) -> Dict[str, Any]:
        gid, member, epoch = self._guard()
        return enter_program_barrier(gid, member, epoch, name,
                                     fingerprint, timeout_s)

    def beat_once(self) -> Dict[str, Any]:
        """One synchronous membership beat (tests drive fencing
        deterministically through this; the background loop is the
        production path)."""
        with self._lock:
            gid = self._ctx["group_id"]
            member = self._ctx["member"]
            epoch = self._ctx["epoch"]
        from ray_tpu.core.rpc_stubs import ControllerStub

        reply = ControllerStub(_controller_client()).mh_member_beat(
            gid, member, epoch, timeout=5.0)
        if reply.get("fenced"):
            with self._lock:
                self._fenced = True
        return reply

    # ----------------------------------------------- jax.distributed

    def reserve_coordinator(self, port: int = 0) -> str:
        from ray_tpu.train.jax_backend import pick_coordinator_address

        return pick_coordinator_address(port)

    def join_gang_runtime(self, group_id: str, epoch: int, member: str,
                          coordinator: str, num_processes: int,
                          process_id: int, platform,
                          local_devices) -> int:
        """Barrier'd jax.distributed join (the shared gang path; see
        :func:`join_jax_gang`)."""
        n = join_jax_gang(group_id, member, epoch, coordinator,
                          num_processes, process_id, platform,
                          local_devices)
        with self._lock:
            self._ctx["coordinator_address"] = coordinator
        return n

    def join_jax(self, timeout_s: Optional[float] = None) -> int:
        """Join the group's jax runtime using the member's OWN aligned
        context (coordinator/process_id/num_processes handed to it at
        election)."""
        gid, member, epoch = self._guard()
        with self._lock:
            ctx = dict(self._ctx)
        coordinator = ctx.get("coordinator_address")
        if not coordinator:
            raise MultihostError(
                f"member {member} has no coordinator address yet "
                f"(election incomplete)")
        return join_jax_gang(
            gid, member, epoch, coordinator, int(ctx["num_processes"]),
            int(ctx["process_id"]), ctx.get("platform"),
            ctx.get("local_device_count"), timeout_s=timeout_s)

    def shutdown_jax(self, timeout: float = 10.0) -> bool:
        """Cooperatively leave the jax.distributed runtime (the
        coordination service runs a shutdown barrier — all ranks must
        call in concurrently; timeout-guarded so a wedged runtime
        cannot hang the actor)."""
        from ray_tpu.train.jax_backend import shutdown_process

        done = threading.Event()

        def run():
            shutdown_process()
            done.set()

        t = threading.Thread(target=run, name="jax-shutdown",
                             daemon=True)
        t.start()
        t.join(timeout)
        return done.is_set()

    # -------------------------------------------------- user payload

    def run(self, fn_blob: bytes, args: tuple = (),
            kwargs: Optional[Dict[str, Any]] = None) -> Any:
        """Execute a user callable on this member: ``fn(member, *args,
        **kwargs)`` where ``member`` is a :class:`MemberRuntime`."""
        from ray_tpu.core import serialization

        self._guard()
        fn = serialization.loads_function(fn_blob)
        return fn(MemberRuntime(self), *args, **(kwargs or {}))

    def stop(self) -> bool:
        self._stop.set()
        return True


# =====================================================================
# The driver-side gang
# =====================================================================

_FORMING = "FORMING"
_ALIVE = "ALIVE"
_RESTARTING = "RESTARTING"
_DEAD = "DEAD"
_SHUTDOWN = "SHUTDOWN"


class HostGroup:
    """A gang-scheduled group of one worker actor per host of an
    ICI-contiguous sub-slice reservation. See the module docstring for
    the contract; the short version:

    * ``start()`` is all-or-nothing: reservation refusal or any member
      spawn failure leaves NOTHING behind (sub-slice released exactly
      once, group record dropped) and raises
      :class:`GangPlacementError`.
    * One member dying reconciles the WHOLE gang: every member is
      killed, the sub-slice is released once, and (restart budget
      permitting) a fresh gang forms under a bumped epoch with a fresh
      coordinator election. Zombie members of the old epoch self-fence.
    * ``broadcast``/``call_all`` fan a payload across the gang.
    """

    def __init__(self, num_hosts: int, *,
                 chips_per_host: Optional[int] = None,
                 name: Optional[str] = None,
                 max_group_restarts: int = 1,
                 worker_options: Optional[Dict[str, Any]] = None,
                 worker_cls: Optional[type] = None,
                 owner: str = ""):
        if num_hosts < 1:
            raise ValueError("num_hosts must be >= 1")
        if worker_cls is not None and not issubclass(worker_cls,
                                                     HostWorker):
            # The gang contract (beat loop, fencing, barrier entry,
            # aligned ctx) lives in HostWorker; a member class that
            # doesn't extend it would silently opt out of epoch fencing.
            raise TypeError(f"worker_cls must extend HostWorker, got "
                            f"{worker_cls!r}")
        self._worker_cls = worker_cls or HostWorker
        self.group_id = name or f"gang-{uuid.uuid4().hex[:8]}"
        self.num_hosts = int(num_hosts)
        self.max_group_restarts = int(max_group_restarts)
        self._chips_per_host = chips_per_host
        self._worker_options = dict(worker_options or {})
        self._owner = owner or f"hostgroup:{self.group_id}"
        self._lock = threading.Lock()
        self._state = "NEW"
        self._members: List[Any] = []
        self._epoch = 0
        self._sub: Optional[Dict[str, Any]] = None
        self._coordinator: Optional[str] = None
        self._coordinator_address: Optional[str] = None
        self._restarts = 0
        self._releases = 0
        self._death_cause: Optional[str] = None
        self._stopped = threading.Event()
        self._monitor: Optional[threading.Thread] = None

    # ------------------------------------------------------ lifecycle

    def start(self) -> "HostGroup":
        with self._lock:
            if self._state != "NEW":
                raise MultihostError(
                    f"group {self.group_id} already started "
                    f"({self._state})")
            self._state = _FORMING
        try:
            self._form()
        except BaseException:
            with self._lock:
                self._state = _DEAD
                self._death_cause = "gang formation failed"
            raise
        with self._lock:
            self._state = _ALIVE
        from ray_tpu.core.config import config

        self._monitor = threading.Thread(
            target=self._monitor_loop,
            args=(config.mh_monitor_period_s,),
            name="hostgroup-monitor", daemon=True)
        self._monitor.start()
        return self

    def _resolve_chips_per_host(self, stub) -> int:
        from ray_tpu.core.config import config

        if self._chips_per_host is not None:
            return int(self._chips_per_host)
        state = stub.topology_state(timeout=config.ctrl_call_timeout_s)
        for s in state.get("slices", {}).values():
            cph = s.get("chips_per_host")
            if cph:
                return int(cph)
        raise GangPlacementError(
            f"group {self.group_id}: no advertised slice to derive "
            f"chips_per_host from (pass chips_per_host=, or advertise "
            f"a slice — RAY_TPU_VIRTUAL_SLICE on dev boxes)")

    def _form(self) -> None:
        """Reserve -> register -> gang-spawn -> elect. The sub-slice
        lease and the group registration are BOTH discharged on every
        exception path between acquisition and the handoff to ``self``
        — a partial spawn must strand nothing (graftlint
        resource-leak-path, at gang granularity). The lease locals
        (``sub``, ``reg``) are only ever read through subscripts inside
        the fallible region: the reservation has no owner record until
        ``_commit_formation`` takes it, so the exception path below is
        the only thing standing between a spawn failure and chips
        stranded until node death."""
        from ray_tpu.core.config import config
        from ray_tpu.core.rpc_stubs import ControllerStub
        from ray_tpu.util.deadline import Deadline

        stub = ControllerStub(_controller_client())
        cph = self._resolve_chips_per_host(stub)
        chips = self.num_hosts * cph
        # One budget covers the whole reserve -> register -> fence
        # sequence: each RPC gets the REMAINING time, not a fresh
        # per-call allowance, so a slow head cannot stretch formation
        # to N x the knob before the spawn phase even starts.
        dl = Deadline.after(config.mh_form_timeout_s)
        with _gang_span("gang:form", group=self.group_id,
                        hosts=self.num_hosts):
            sub = stub.reserve_subslice(self._owner, chips,
                                        timeout=dl.remaining())
            if sub is None:
                # The controller's refusal already fed _pending_demand
                # (the autoscaler sees a gang that could not place).
                # graftlint: disable=metrics-label-cardinality (gang ids bounded by live gangs; bounded ring)
                flightrec.record("gang.refused", group=self.group_id,
                                 hosts=self.num_hosts, chips=chips)
                raise GangPlacementError(
                    f"no contiguous {chips}-chip sub-slice for a "
                    f"{self.num_hosts}-host gang (chips_per_host={cph});"
                    f" refusal recorded as autoscaler pending demand")
            members = []
            try:
                reg = stub.mh_register_group(self.group_id,
                                             self.num_hosts,
                                             None, self._owner,
                                             timeout=dl.remaining())
                # The fenced write's verdict matters even during
                # formation: a stale epoch here means a concurrent
                # re-registration already owns the group — spawning
                # members against it would form a zombie gang. The
                # verdict is consumed in test position and the raise
                # message stays off ``reg``: assignment values and
                # raise expressions transfer lease ownership to the
                # lifetime checker, which would mask the
                # _abort_formation leak edges (the docstring's
                # subscript-only-read invariant).
                if not (stub.mh_group_put(self.group_id, "reservation",
                                          sub["reservation_id"],
                                          int(reg["epoch"]),
                                          timeout=dl.remaining())
                        or {}).get("ok"):
                    raise GroupEpochFenced(
                        f"reservation write for group {self.group_id} "
                        "rejected: a newer registration owns the epoch")
                self._spawn_members_into(
                    members, int(reg["epoch"]), sub["reservation_id"],
                    sub["slice_id"], sub["nodes"], sub["origin"],
                    sub["shape"], cph)
                self._elect(members, int(reg["epoch"]))
            except BaseException as e:
                # Release-once on partial-spawn failure: the
                # half-created group record drops and the chips go back
                # to the grid.
                self._abort_formation(stub, sub["reservation_id"])
                if isinstance(e, MultihostError):
                    raise
                raise GangPlacementError(
                    f"gang spawn for group {self.group_id} failed: "
                    f"{e!r}") from e
        # Ownership handoff FIRST: the group object owns the
        # reservation from here (release_reservation_once / shutdown
        # discharge it), so the record below can never strand it.
        self._commit_formation(sub, reg, members)
        # Gang ids are bounded by live gangs (the recorder ring is
        # bounded regardless); the id IS the evidence.
        # graftlint: disable=metrics-label-cardinality
        flightrec.record("gang.form", group=self.group_id,
                         epoch=int(reg["epoch"]), hosts=self.num_hosts)

    def _abort_formation(self, stub, reservation_id: str) -> None:
        """Partial-spawn cleanup: hand the chips back and drop the
        half-registered group record — each best-effort in its own
        guard, so a head blip during one cannot strand the other (a
        failed release is logged; node-death reclamation is the
        backstop) — before the formation error propagates."""
        from ray_tpu.core.config import config

        try:
            stub.release_subslice(reservation_id,
                                  timeout=config.ctrl_call_timeout_s)
        except Exception:
            log_every("multihost.abort_release", 10.0, logger,
                      "releasing sub-slice %s during formation abort "
                      "failed", reservation_id, exc_info=True)
        try:
            stub.mh_drop_group(self.group_id,
                               timeout=config.ctrl_call_timeout_s)
        except Exception:
            log_every("multihost.abort_drop", 10.0, logger,
                      "dropping group %s during formation abort failed",
                      self.group_id, exc_info=True)

    def _commit_formation(self, sub: Dict[str, Any],
                          reg: Dict[str, Any],
                          members: List[Any]) -> None:
        with self._lock:
            self._sub = sub
            self._epoch = int(reg["epoch"])
            self._members = list(members)

    def _spawn_members_into(self, members: List[Any], epoch: int,
                            reservation_id: str, slice_id: str,
                            nodes: List[str], origin: List[int],
                            shape: List[int], cph: int) -> None:
        """One HostWorker per host, all-or-nothing: every member gets a
        disjoint chip mask covering the sub-slice and the same group
        geometry; any failure kills whatever spawned. Appends into
        ``members`` (the caller's list) rather than returning so the
        lease locals in ``_form`` stay subscript-read borrows."""
        import ray_tpu
        from ray_tpu.core.config import config
        from ray_tpu.core.placement import NodeAffinitySchedulingStrategy

        chip_ids = [[origin[0] + i, origin[1] + j]
                    for i in range(shape[0]) for j in range(shape[1])]
        # Formation-time taint consult (autopilot taint-host action):
        # the reservation's node list is already untainted-first, but
        # taints move between reserve and spawn — and a RE-formation
        # after a member death is exactly when a freshly-demoted host
        # must not get the gang back. Best-effort: an unreachable head
        # changes nothing (empty taint set = legacy order).
        if nodes:
            try:
                from ray_tpu.core.rpc_stubs import ControllerStub
                taints = ControllerStub(_controller_client()).taint_state(
                    timeout=config.ctrl_call_timeout_s)
            except Exception:
                taints = {}
            if taints:
                nodes = ([n for n in nodes if n not in taints]
                         + [n for n in nodes if n in taints])
        actor_cls = ray_tpu.remote(self._worker_cls)
        try:
            for rank in range(self.num_hosts):
                ctx = {
                    "group_id": self.group_id,
                    "member": member_name(rank),
                    "process_id": rank,
                    "num_processes": self.num_hosts,
                    "epoch": epoch,
                    "reservation_id": reservation_id,
                    "slice_id": slice_id,
                    "chips_per_host": cph,
                    "local_device_ids":
                        chip_ids[rank * cph:(rank + 1) * cph],
                    "local_device_count": cph,
                }
                opts = dict(self._worker_options)
                opts.setdefault("max_concurrency", 8)
                if nodes and "scheduling_strategy" not in opts:
                    opts["scheduling_strategy"] = \
                        NodeAffinitySchedulingStrategy(
                            nodes[rank % len(nodes)])
                members.append(actor_cls.options(**opts).remote(ctx))
            # Gang formation check: every member must come up before
            # the group exists at all.
            ray_tpu.get([m.ping.remote() for m in members],
                        timeout=config.mh_form_timeout_s)
        except BaseException:
            self._kill_members(members)
            del members[:]
            raise

    def _elect(self, members: List[Any], epoch: int) -> None:
        """Coordinator election: the lowest live member index wins
        (every formation has a full fresh gang, so that is rank 0 of
        THIS epoch), picks the address the rest will join, and the
        result is recorded as a FENCED group-KV write — a deposed
        coordinator replaying its election is rejected, not applied.
        Every member then receives the same (address, coordinator,
        epoch) triple: aligned visibility by construction."""
        import ray_tpu
        from ray_tpu.core.config import config
        from ray_tpu.core.rpc_stubs import ControllerStub

        coordinator = member_name(0)
        with _gang_span("gang:elect", group=self.group_id, epoch=epoch):
            coord_addr = ray_tpu.get(
                members[0].reserve_coordinator.remote(0), timeout=60.0)
            put = ControllerStub(_controller_client()).mh_group_put(
                self.group_id, "coordinator",
                {"member": coordinator, "address": coord_addr,
                 "epoch": epoch}, epoch,
                timeout=config.ctrl_call_timeout_s)
            if not put.get("ok"):
                raise GroupEpochFenced(
                    f"election write for group {self.group_id} epoch "
                    f"{epoch} rejected: {put!r}")
            ray_tpu.get([m.configure.remote(coord_addr, coordinator,
                                            epoch)
                         for m in members], timeout=60.0)
        # graftlint: disable=metrics-label-cardinality (gang ids bounded by live gangs; bounded ring)
        flightrec.record("gang.elect", group=self.group_id, epoch=epoch,
                         coordinator=coordinator)
        with self._lock:
            self._coordinator = coordinator
            self._coordinator_address = coord_addr

    # ------------------------------------------------------- monitor

    def _monitor_loop(self, period: float) -> None:
        from ray_tpu.core.config import config

        while not self._stopped.wait(period):
            with self._lock:
                if self._state != _ALIVE:
                    continue
                members = list(self._members)
            dead: List[int] = []
            for i, m in enumerate(members):
                import ray_tpu

                try:
                    ray_tpu.get(m.ping.remote(),
                                timeout=config.mh_ping_timeout_s)
                except Exception:
                    dead.append(i)
            if not dead:
                victim = (self._poll_autopilot_eviction()
                          if config.autopilot_enabled else None)
                if victim is None:
                    continue
                with self._lock:
                    if self._state != _ALIVE or self._members != members:
                        continue
                self._reconcile([victim])
                continue
            with self._lock:
                # The gang may have been replaced while we pinged the
                # old incarnation; only reconcile the CURRENT members.
                if self._state != _ALIVE or self._members != members:
                    continue
            self._reconcile([member_name(i) for i in dead])

    def _poll_autopilot_eviction(self) -> Optional[str]:
        """Autopilot's reschedule-gang action arrives as a FENCED
        group-KV write (key ``autopilot_evict``, fenced on the epoch
        the autopilot observed — the registry already rejected any
        stale write, and re-registration clears the key with the rest
        of the group KV, so a consumed eviction dies with its epoch).
        The monitor treats the named member as dead, funnelling the
        action through the exact same epoch-fenced reconcile path as a
        real member death: never a double kill. Only polled when
        config.autopilot_enabled — the OFF path does not even RPC."""
        from ray_tpu.core.config import config
        from ray_tpu.core.rpc_stubs import ControllerStub

        try:
            victim = ControllerStub(_controller_client()).mh_group_get(
                self.group_id, "autopilot_evict",
                timeout=config.ctrl_call_timeout_s)
        except Exception:
            return None
        if not isinstance(victim, str):
            return None
        valid = {member_name(i) for i in range(self.num_hosts)}
        return victim if victim in valid else None

    def _reconcile(self, dead_members: List[str]) -> None:
        """Death reconciliation: the WHOLE gang dies as a unit (no
        half-alive meshes), the sub-slice is released exactly once,
        and — restart budget permitting — a fresh gang forms under a
        bumped epoch with a fresh coordinator election. Survivors of
        the old epoch that were merely unreachable self-fence on their
        next beat."""
        with self._lock:
            if self._state != _ALIVE:
                return
            self._state = _RESTARTING
            members = self._members
            self._members = []
            coordinator_died = self._coordinator in dead_members
            cause = (f"member(s) {', '.join(dead_members)} died"
                     + (" (coordinator — re-electing)"
                        if coordinator_died else ""))
            self._death_cause = cause
            old_epoch = self._epoch
        logger.info("host group %s: %s; reconciling the whole gang",
                    self.group_id, cause)
        # The monitor names the dead IN DETECTION ORDER: dead[0] is the
        # post-mortem's "first-dying member" (corroborated by the
        # victim's own recorder going silent / a fault.fired die event).
        # graftlint: disable=metrics-label-cardinality (gang ids bounded by live gangs; bounded ring)
        flightrec.record("gang.reconcile", group=self.group_id,
                         epoch=old_epoch, dead=",".join(dead_members),
                         coordinator_died=coordinator_died)
        with _gang_span("gang:reconcile", group=self.group_id,
                        epoch=old_epoch, dead=",".join(dead_members)):
            self._kill_members(members)
            self.release_reservation_once()
            restart = False
            with self._lock:
                if self._restarts < self.max_group_restarts:
                    self._restarts += 1
                    restart = True
            if restart:
                try:
                    self._form()
                except Exception as e:
                    # graftlint: disable=metrics-label-cardinality (gang ids bounded by live gangs; bounded ring)
                    flightrec.record("gang.dead", group=self.group_id,
                                     epoch=old_epoch,
                                     cause=f"restart failed: {e!r}")
                    with self._lock:
                        self._state = _DEAD
                        self._death_cause = (
                            f"{self._death_cause}; restart failed: "
                            f"{e!r}")
                    return
                with self._lock:
                    # shutdown() may have run while the fresh gang was
                    # forming (it found nothing to tear down then): the
                    # re-formed gang must not outlive the group object.
                    stale = self._stopped.is_set()
                    if stale:
                        members = self._members
                        self._members = []
                    else:
                        # death_cause stays as the last-reconciliation
                        # record (status() history), state returns to
                        # life.
                        self._state = _ALIVE
                if stale:
                    self._kill_members(members)
                    self.release_reservation_once()
                    drop_gang(self.group_id)
                return
            # graftlint: disable=metrics-label-cardinality (gang ids bounded by live gangs; bounded ring)
            flightrec.record("gang.dead", group=self.group_id,
                             epoch=old_epoch,
                             cause="restart budget exhausted")
            drop_gang(self.group_id)
            with self._lock:
                self._state = _DEAD

    def _kill_members(self, members: List[Any]) -> None:
        import ray_tpu

        for m in members:
            try:
                ray_tpu.kill(m)
            except Exception:  # graftlint: disable=swallowed-exception (best-effort gang teardown; the cluster reaps dead workers)
                pass

    def release_reservation_once(self) -> bool:
        """Hand the sub-slice back to the topology view EXACTLY once
        (the swap under the lock is the once-guard; the release RPC
        itself is idempotent on the head, and node-death reclamation is
        the backstop if the head is unreachable)."""
        with self._lock:
            sub, self._sub = self._sub, None
        if sub is None:
            return False
        from ray_tpu.core.config import config
        from ray_tpu.core.rpc_stubs import ControllerStub

        try:
            ControllerStub(_controller_client()).release_subslice(
                sub["reservation_id"],
                timeout=config.ctrl_call_timeout_s)
        except Exception:
            log_every("multihost.release", 10.0, logger,
                      "releasing sub-slice %s of group %s failed "
                      "(node-death reclamation is the backstop)",
                      sub["reservation_id"], self.group_id,
                      exc_info=True)
        with self._lock:
            self._releases += 1
        return True

    def shutdown(self) -> None:
        self._stopped.set()
        with self._lock:
            if self._state == _SHUTDOWN:
                return
            self._state = _SHUTDOWN
            members = self._members
            self._members = []
            epoch = self._epoch
        # graftlint: disable=metrics-label-cardinality (gang ids bounded by live gangs; bounded ring)
        flightrec.record("gang.shutdown", group=self.group_id,
                         epoch=epoch)
        self._kill_members(members)
        self.release_reservation_once()
        drop_gang(self.group_id)

    # ------------------------------------------------------- surface

    @property
    def members(self) -> List[Any]:
        with self._lock:
            return list(self._members)

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def coordinator(self) -> Optional[Dict[str, Any]]:
        """The current election record, from the group's fenced KV."""
        from ray_tpu.core.config import config
        from ray_tpu.core.rpc_stubs import ControllerStub

        return ControllerStub(_controller_client()).mh_group_get(
            self.group_id, "coordinator",
            timeout=config.ctrl_call_timeout_s)

    def call_all(self, method: str, *args,
                 timeout: Optional[float] = None, **kwargs) -> List[Any]:
        """Invoke one method on every member concurrently; returns the
        results in member order (all-or-nothing: any member failing
        raises)."""
        import ray_tpu

        refs = [getattr(m, method).remote(*args, **kwargs)
                for m in self.members]
        return ray_tpu.get(refs, timeout=timeout)

    def broadcast(self, fn, *args, timeout: Optional[float] = None,
                  **kwargs) -> List[Any]:
        """Run ``fn(member_runtime, *args, **kwargs)`` on every member
        concurrently (the gang-wide user-payload helper)."""
        from ray_tpu.core import serialization

        fn_blob = serialization.dumps_function(fn)
        return self.call_all("run", fn_blob, args, kwargs,
                             timeout=timeout)

    def form_mesh(self, *, timeout: float = 120.0) -> List[int]:
        """Join every member into one global jax runtime (real rigs;
        the CPU backend cannot run the resulting collectives — jaxlib
        0.4.37). Uses each member's own aligned context."""
        return self.call_all("join_jax", timeout=timeout)

    def status(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "group_id": self.group_id,
                "state": self._state,
                "epoch": self._epoch,
                "num_hosts": self.num_hosts,
                "restarts": self._restarts,
                "releases": self._releases,
                "death_cause": self._death_cause,
                "coordinator": self._coordinator,
                "coordinator_address": self._coordinator_address,
                "sub_slice": dict(self._sub) if self._sub else None,
            }
        try:
            out["registry"] = registry_state(self.group_id)
        except Exception:
            out["registry"] = None
        return out
