"""@remote functions: the task-submission frontend.

Analogue of the reference's ``python/ray/remote_function.py``: wraps a Python
function, exports its pickled form once to the controller KV (reference:
``_private/function_manager.py`` exports to GCS KV), and submits invocations
through the core worker. ``.options(...)`` returns a shallow clone with
overridden submission options, exactly like the reference API.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Dict, Optional

from ray_tpu.core import serialization
from ray_tpu.core.runtime import get_core_worker

_exported_keys = set()
_export_lock = threading.Lock()


def _resources_from_options(opts: Dict[str, Any]) -> Dict[str, float]:
    resources = dict(opts.get("resources") or {})
    num_cpus = opts.get("num_cpus")
    if num_cpus is None:
        num_cpus = 1.0 if "CPU" not in resources else resources.pop("CPU")
    resources["CPU"] = float(num_cpus)
    if opts.get("num_tpus"):
        resources["TPU"] = float(opts["num_tpus"])
    if opts.get("num_gpus"):
        # GPUs do not exist on the TPU path; accept the kwarg for API parity
        # and model it as a generic resource so tests/configs still schedule.
        resources["GPU"] = float(opts["num_gpus"])
    if resources["CPU"] == 0.0:
        del resources["CPU"]
    return resources


def export_callable(fn) -> tuple:
    """Pickle ``fn`` once and publish it to the controller KV once per
    cluster (reference: function export to GCS KV,
    ``_private/function_manager.py``). The pickle + hash is cached on the
    function object, and the KV write is synchronous before any task ships,
    so task specs carry only the key — workers fetch from KV on first use and
    cache by key. Returns (key, blob)."""
    # Read the cache from fn's OWN __dict__, never via getattr: classes
    # inherit attributes through the MRO, so after exporting a base
    # class, getattr on a SUBCLASS would return the base's cached
    # (key, blob) and every remote spawn of the subclass would silently
    # instantiate the base class on the worker.
    own = getattr(fn, "__dict__", None)
    cached = own.get("__ray_tpu_export__") if own is not None else None
    if cached is None:
        blob = serialization.dumps_function(fn)
        key = "fn:" + hashlib.sha256(blob).hexdigest()[:32]
        cached = (key, blob)
        try:
            fn.__ray_tpu_export__ = cached
        except (AttributeError, TypeError):
            pass  # builtins etc.: re-pickle per call
    key, blob = cached
    core = get_core_worker()
    with _export_lock:
        exported = key in _exported_keys
    if not exported:
        # The KV write happens OUTSIDE _export_lock: holding it across
        # the RPC would serialize every first-submit of every function
        # behind one controller round-trip (graftlint:
        # lock-held-blocking). Keys are content-addressed, so a
        # concurrent duplicate put is idempotent — worst case one
        # redundant RPC, never a wrong value.
        core.controller.call("kv_put", key, blob, False)
        with _export_lock:
            _exported_keys.add(key)
    return key, blob


class RemoteFunction:
    def __init__(self, fn, options: Optional[Dict[str, Any]] = None):
        self._fn = fn
        self._options = dict(options or {})
        self._desc = getattr(fn, "__qualname__", repr(fn))
        self.__name__ = getattr(fn, "__name__", "remote_function")

    def options(self, **overrides) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(overrides)
        return RemoteFunction(self._fn, merged)

    def remote(self, *args, **kwargs):
        core = get_core_worker()
        key, _ = export_callable(self._fn)
        opts = self._options
        submit_options = {
            "resources": _resources_from_options(opts),
            "num_returns": opts.get("num_returns", 1),
            "max_retries": opts.get("max_retries", 3),
            "retry_on_crash": opts.get("max_retries", 3) != 0,
            "scheduling_strategy": _strategy_dict(opts.get("scheduling_strategy")),
            "placement": _placement_tuple(opts),
            "runtime_env": _normalized_env(opts),
            "inline_results": opts.get("inline_results", True),
        }
        refs = core.submit_task(key, self._desc, args, kwargs,
                                submit_options)
        if submit_options["num_returns"] == 1:
            return refs[0]
        return refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._desc} cannot be called directly; "
            f"use .remote().")


def _normalized_env(opts) -> Optional[Dict[str, Any]]:
    """Validate the runtime_env at SUBMISSION time (typos and bad types
    fail in the driver, not as a lease error minutes later)."""
    spec = opts.get("runtime_env")
    if not spec:
        return None
    from ray_tpu.runtime_env import normalize

    return normalize(spec)


def _strategy_dict(strategy) -> Optional[Dict[str, Any]]:
    if strategy is None:
        return None
    if isinstance(strategy, str):
        return {"kind": strategy.lower()}
    if isinstance(strategy, dict):
        return strategy
    # PlacementGroupSchedulingStrategy is handled by _placement_tuple.
    if hasattr(strategy, "placement_group"):
        return None
    # NodeAffinitySchedulingStrategy-like object
    if hasattr(strategy, "node_id"):
        return {"kind": "node_affinity", "node_id": strategy.node_id,
                "soft": getattr(strategy, "soft", False)}
    raise TypeError(f"unknown scheduling strategy {strategy!r}")


def _placement_tuple(opts) -> Optional[tuple]:
    pg = opts.get("placement_group")
    if pg is None:
        strategy = opts.get("scheduling_strategy")
        if hasattr(strategy, "placement_group"):
            pg = strategy.placement_group
            index = getattr(strategy, "placement_group_bundle_index", 0)
            return (pg.id.binary(), index)
        return None
    index = opts.get("placement_group_bundle_index", 0)
    return (pg.id.binary(), index)
