"""Controller-side pipeline registry: the ``pipe_*`` RPC surface.

The pipeline-parallel training plane (``ray_tpu/train/pipeline_plane``)
is a driver-side scheduler over a gang of stage actors; what must
OUTLIVE any single driver step — and be fenced against a deposed
incarnation after a whole-gang restart — is tiny: which pipelines
exist, their geometry, and the **last completed optimizer step** under
the **current epoch**. This registry is that record, built on the same
three idioms as the host-group registry (``core/multihost.py``):

* re-registering an existing pipeline id bumps a **monotonic epoch**
  (the whole-gang-restart path: the re-formed gang re-registers and
  every write from the old incarnation turns stale);
* ``step_complete`` is **fenced** — a stale-epoch writer gets
  ``{"ok": False, "reason": "stale_epoch"}`` back and must self-fence
  instead of moving the step clock backwards for the live gang;
* ``state`` is the operator/test view (``ray_tpu doctor``'s
  pipeline-stall evidence names pipelines through it).

Progress only ever moves FORWARD under one epoch: ``last_step`` is a
max, so a duplicate or re-ordered completion report is idempotent.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional


class _PipeRecord:
    __slots__ = ("pipeline_id", "num_stages", "group_id", "owner",
                 "epoch", "last_step", "registered_at", "last_report")

    def __init__(self, pipeline_id: str, num_stages: int, group_id: str,
                 owner: str):
        self.pipeline_id = pipeline_id
        self.num_stages = int(num_stages)
        self.group_id = group_id
        self.owner = owner
        self.epoch = 1
        self.last_step = -1
        self.registered_at = time.monotonic()
        self.last_report = None


class PipelineRegistry:
    """Pipeline records keyed by pipeline id. All handlers run on the
    controller's RPC pool threads; everything is O(1) under one lock
    (no parked waiters — the plane's scheduling loop lives driver-side,
    only durable-ish progress facts land here)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pipes: Dict[str, _PipeRecord] = {}

    # ------------------------------------------------------- handlers

    def register(self, pipeline_id: str, num_stages: int,
                 group_id: str = "", owner: str = "") -> Dict[str, Any]:
        """Create a pipeline record, or RE-register an existing id —
        the whole-gang-restart path: the epoch bumps (fencing every
        in-flight step report of the previous incarnation) while
        ``last_step`` is KEPT, because it is exactly the resume point
        the re-formed gang asks for."""
        with self._lock:
            rec = self._pipes.get(pipeline_id)
            if rec is None:
                rec = _PipeRecord(pipeline_id, num_stages, group_id,
                                  owner)
                self._pipes[pipeline_id] = rec
            else:
                rec.epoch += 1
                rec.num_stages = int(num_stages)
                rec.group_id = group_id
            return {"epoch": rec.epoch, "last_step": rec.last_step}

    def drop(self, pipeline_id: str) -> bool:
        """Unregister (idempotent)."""
        with self._lock:
            return self._pipes.pop(pipeline_id, None) is not None

    def step_complete(self, pipeline_id: str, step: int,
                      epoch: int) -> Dict[str, Any]:
        """Record one completed optimizer step, fenced by epoch: a
        writer from a deposed gang incarnation is rejected (it must
        self-fence), and within the live epoch progress is a max —
        duplicate reports are idempotent."""
        with self._lock:
            rec = self._pipes.get(pipeline_id)
            if rec is None:
                return {"ok": False, "reason": "unknown_pipeline"}
            if epoch < rec.epoch:
                return {"ok": False, "reason": "stale_epoch",
                        "epoch": rec.epoch}
            rec.last_step = max(rec.last_step, int(step))
            rec.last_report = time.monotonic()
            return {"ok": True, "last_step": rec.last_step,
                    "epoch": rec.epoch}

    def state(self, pipeline_id: Optional[str] = None) -> Any:
        """Operator/test view of registered pipelines."""
        now = time.monotonic()

        def summary(rec: _PipeRecord) -> Dict[str, Any]:
            return {
                "pipeline_id": rec.pipeline_id,
                "num_stages": rec.num_stages,
                "group_id": rec.group_id,
                "owner": rec.owner,
                "epoch": rec.epoch,
                "last_step": rec.last_step,
                "age_s": round(now - rec.registered_at, 3),
                "report_age_s": (None if rec.last_report is None
                                 else round(now - rec.last_report, 3)),
            }

        with self._lock:
            if pipeline_id is not None:
                rec = self._pipes.get(pipeline_id)
                return summary(rec) if rec is not None else None
            return {p: summary(rec) for p, rec in self._pipes.items()}
