"""Per-node log monitor + driver-side log streaming.

Reference analogue: ``python/ray/_private/log_monitor.py:103`` — workers
write stdout/stderr to per-worker files under the session log dir; a
per-node monitor tails them and publishes new lines to GCS pubsub; drivers
subscribe and echo the lines prefixed with the producing worker.

Here the monitor rides the controller's versioned long-poll pubsub
(``core/pubsub.py``). Because that hub stores only the *latest* value per
key, each publish carries a cumulative window of the last
``log_window_lines`` lines plus a monotonically increasing end counter —
the driver diffs counters to print exactly the unseen suffix, so bursts
between polls are never lost (up to the window size).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, List, Optional, Tuple

from ray_tpu.core.config import config
from ray_tpu.util.ratelimit import log_every

logger = logging.getLogger(__name__)

LOG_CHANNEL = "logs"

# Transport slack ABOVE the long-poll window on every streamer RPC: the
# bound that turns a dead head into a typed RpcTimeout (the _loop's
# catch-and-backoff path) instead of a silently parked log pump.
_RPC_SLACK_S = 10.0


def worker_log_paths(node_hex: str, worker_hex: str) -> Tuple[str, str]:
    d = os.path.join(config.worker_log_dir, node_hex)
    os.makedirs(d, exist_ok=True)
    short = worker_hex[:8]
    return (os.path.join(d, f"worker-{short}.out"),
            os.path.join(d, f"worker-{short}.err"))


class LogMonitor:
    """Tails every worker log file under this node's log dir and publishes
    appended lines to the controller pubsub (one key per node)."""

    def __init__(self, node):
        self._node = node
        self._dir = os.path.join(config.worker_log_dir, node.node_id.hex())
        os.makedirs(self._dir, exist_ok=True)
        self._offsets: Dict[str, int] = {}
        self._window: List[Tuple[str, str]] = []  # (tag, line)
        self._end = 0  # lines ever published
        self._scan_lock = threading.Lock()  # scan_once callable off-thread
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="log-monitor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

    def _loop(self) -> None:
        while not self._stopped.wait(config.log_monitor_scan_s):
            try:
                self.scan_once()
            except Exception:
                log_every("log_monitor.scan", 60.0, logger,
                          "log scan pass failed", exc_info=True)

    def scan_once(self) -> int:
        """Read appended bytes from every log file; publish if new lines.
        Serialized by a lock: the shutdown drain calls this concurrently
        with the scan thread."""
        with self._scan_lock:
            return self._scan_once_locked()

    def _scan_once_locked(self) -> int:
        new: List[Tuple[str, str]] = []
        try:
            names = sorted(os.listdir(self._dir))
        except OSError:
            return 0
        for name in names:
            path = os.path.join(self._dir, name)
            off = self._offsets.get(name, 0)
            try:
                size = os.path.getsize(path)
                if size <= off:
                    continue
                with open(path, "rb") as f:
                    f.seek(off)
                    data = f.read(size - off)
            except OSError:
                continue
            # Only consume complete lines; a partially written tail stays
            # for the next scan.
            cut = data.rfind(b"\n")
            if cut < 0:
                continue
            self._offsets[name] = off + cut + 1
            # Rotation: once the consumed prefix passes the cap, truncate
            # the file in place (workers write O_APPEND, so writes continue
            # at the new end; a line landing between read and truncate is
            # lost, which rotation accepts by design).
            if self._offsets[name] > config.log_rotation_max_bytes:
                try:
                    os.truncate(path, 0)
                    self._offsets[name] = 0
                except OSError:
                    pass
            tag = name.rsplit(".", 1)[0] + (
                ":err" if name.endswith(".err") else "")
            for raw in data[:cut].split(b"\n"):
                line = raw.decode("utf-8", "replace").rstrip("\r")
                if line:
                    new.append((tag, line))
        if not new:
            return 0
        self._window.extend(new)
        del self._window[:-config.log_window_lines]
        self._end += len(new)
        try:
            self._node._controller.notify(
                "psub_publish", LOG_CHANNEL, self._node.node_id.hex(),
                {"end": self._end, "window": list(self._window)})
        except Exception:
            # Lines stay in the window; the next scan republishes them.
            log_every("log_monitor.publish", 60.0, logger,
                      "log window publish failed", exc_info=True)
        return len(new)


class LogStreamer:
    """Driver-side subscriber: long-polls the logs channel for every node
    and echoes unseen lines to this process's stdout, prefixed with the
    producing worker (reference: log lines proxied to the driver with
    ``(pid=…, ip=…)`` prefixes)."""

    def __init__(self, controller_client, out=None):
        self._controller = controller_client
        self._out = out  # defaults to sys.stdout at print time
        self._seen: Dict[str, int] = {}  # node hex -> last end counter
        self._versions: Dict[str, int] = {}  # node hex -> pubsub version
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="log-streamer", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

    def _loop(self) -> None:
        while not self._stopped.is_set():
            try:
                self.poll_once(window_s=5.0)
            except Exception:
                if self._stopped.wait(1.0):
                    return

    def poll_once(self, window_s: float = 5.0) -> int:
        """One long-poll round; returns number of lines printed.
        ``window_s`` is the server-side long-poll WINDOW (how long the
        head may hold the poll open waiting for new lines), not a call
        budget — each RPC below carries a transport bound of the window
        plus slack, so a dead head surfaces as a typed timeout rather
        than a parked streamer. Key discovery is version-only
        (psub_keys) — window payloads transfer only for keys that
        actually advanced."""
        keymap = self._controller.call("psub_keys", LOG_CHANNEL,
                                       timeout=window_s + _RPC_SLACK_S)
        printed = 0
        behind = {key: ver for key, ver in keymap.items()
                  if ver > self._versions.get(key, 0)}
        if behind:
            # Fetch just the advanced keys (version-1 so poll returns the
            # current value immediately).
            updates = self._controller.call(
                "psub_poll_many",
                {k: (LOG_CHANNEL, k, v - 1) for k, v in behind.items()},
                0.5, timeout=window_s + _RPC_SLACK_S)
            for key, (version, value) in (updates or {}).items():
                printed += self._emit(key, value)
                self._versions[key] = version
        if not keymap:
            # No node has published logs yet; re-check soon rather than
            # sleeping a full long-poll period (first-line latency).
            self._stopped.wait(min(window_s, 1.0))
            return printed
        watches = {key: (LOG_CHANNEL, key, self._versions.get(key, 0))
                   for key in keymap}
        updates = self._controller.call(
            "psub_poll_many", watches, window_s,
            timeout=window_s + _RPC_SLACK_S)
        for key, (version, value) in (updates or {}).items():
            printed += self._emit(key, value)
            self._versions[key] = version
        return printed

    def _emit(self, node_hex: str, value: dict) -> int:
        import sys

        end = value.get("end", 0)
        window = value.get("window", [])
        last = self._seen.get(node_hex, 0)
        fresh = min(end - last, len(window))
        if fresh <= 0:
            self._seen[node_hex] = max(last, end)
            return 0
        out = self._out or sys.stdout
        for tag, line in window[-fresh:]:
            print(f"({tag}, node={node_hex[:8]}) {line}", file=out)
        self._seen[node_hex] = end
        return fresh
