"""TPU topology as first-class scheduler state: chips, hosts, pod slices
and ICI adjacency.

The reference schedules TPUs as an opaque scalar (``"TPU": n`` plus the
``TPU-{pod_type}-head`` gang hack, ``_private/accelerators/tpu.py:381``);
that cannot express the property GSPMD serving actually needs: a replica's
devices must be **ICI-contiguous** — a rectangle of the slice's chip grid,
never a fragment straddling two slices (DCN between slices is ~100x slower
than ICI, and a mesh whose "model" axis crosses it would put every
all-gather on the slow network).

This module is the host-side model the controller schedules against:

* :class:`SliceInfo` — what a node advertises: its slice id, the slice's
  chip-grid topology (an ICI torus footprint like ``(4, 4)``), and chips
  per host. The dev box advertises a *virtual* slice over the 8-device
  CPU mesh (``--xla_force_host_platform_device_count=8``).
* :class:`SliceGrid` — allocator for one slice: reserves aligned,
  contiguous rectangular sub-slices (buddy-style: origins are multiples
  of the block shape, so frees coalesce and fragmentation stays bounded),
  tracks per-chip occupancy and fragmentation.
* :class:`TopologyView` — the controller's cluster-wide view: all
  advertised slices, best-fit sub-slice reservation that NEVER spans two
  slices, release, and an operator-readable state summary.

Pure host arithmetic — no jax import at module level (the controller
process must never pay a backend init for scheduling decisions).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

_reservation_ids = itertools.count(1)


@dataclass(frozen=True)
class SliceInfo:
    """One pod slice as a node advertises it."""

    slice_id: str
    topology: Tuple[int, int]   # chip grid (x, y): the ICI footprint
    chips_per_host: int = 4

    @property
    def chips(self) -> int:
        return self.topology[0] * self.topology[1]

    @property
    def hosts(self) -> int:
        return max(1, self.chips // self.chips_per_host)

    def to_dict(self) -> Dict[str, Any]:
        return {"slice_id": self.slice_id,
                "topology": list(self.topology),
                "chips_per_host": self.chips_per_host}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "SliceInfo":
        return SliceInfo(d["slice_id"], tuple(d["topology"]),
                         int(d.get("chips_per_host", 4)))


def parse_topology(spec: str) -> Tuple[int, int]:
    """``"2x4"`` -> (2, 4); a bare chip count folds to its most-square
    grid (``"8"`` -> (2, 4))."""
    spec = spec.strip().lower()
    if "x" in spec:
        a, b = spec.split("x", 1)
        return (int(a), int(b))
    return most_square(int(spec))


def most_square(chips: int) -> Tuple[int, int]:
    """The most-square (a, b) with a*b == chips and a <= b: the shape a
    chip-count reservation asks for when the caller has no mesh in mind
    (minimizes ICI hop diameter for a given footprint)."""
    if chips < 1:
        raise ValueError(f"chips must be positive, got {chips}")
    a = int(chips ** 0.5)
    while a > 1 and chips % a:
        a -= 1
    return (a, chips // a)


def detect_slice(resources: Optional[Dict[str, float]] = None,
                 node_hint: str = "") -> Optional[SliceInfo]:
    """What slice (if any) this node should advertise.

    Real TPU: ``TPU_ACCELERATOR_TYPE`` / the detected ``TPU`` resource
    give the slice type; the slice id comes from ``TPU_WORKER_HOSTNAMES``
    -style pod metadata when present (all hosts of one slice must agree).
    Dev box: ``RAY_TPU_VIRTUAL_SLICE`` (e.g. ``"2x4"`` or ``"8"``) opts a
    CPU node into advertising a virtual slice over the forced host
    devices — serving tests and the single-process GSPMD path use this.
    An optional ``/N`` suffix (``"4x4/4"``) sets chips-per-host below
    the full slice, making the single dev-box node advertise a virtual
    MULTI-host slice (4x4 grid, 4 chips per host = 4 hosts) — the gang
    substrate (core/multihost.py) spawns one member per virtual host
    against it, the multi-raylet-in-one-machine trick at host
    granularity. Returns None when the node has no accelerator story
    (pure CPU nodes stay out of the topology view entirely)."""
    virt = os.environ.get("RAY_TPU_VIRTUAL_SLICE")
    if virt:
        spec, _, cph = virt.partition("/")
        topo = parse_topology(spec)
        return SliceInfo(f"virtual-{node_hint or os.getpid()}", topo,
                         chips_per_host=(int(cph) if cph
                                         else topo[0] * topo[1]))
    chips = int((resources or {}).get("TPU", 0))
    if chips <= 0:
        return None
    pod_type = os.environ.get("TPU_ACCELERATOR_TYPE", f"tpu-{chips}")
    slice_id = os.environ.get("TPU_SLICE_ID") or pod_type
    return SliceInfo(slice_id, most_square(chips))


@dataclass(frozen=True)
class SubSlice:
    """A reserved contiguous rectangle of one slice's chip grid."""

    reservation_id: str
    slice_id: str
    origin: Tuple[int, int]
    shape: Tuple[int, int]

    @property
    def chips(self) -> int:
        return self.shape[0] * self.shape[1]

    def chip_ids(self) -> List[Tuple[int, int]]:
        ox, oy = self.origin
        return [(ox + i, oy + j) for i in range(self.shape[0])
                for j in range(self.shape[1])]

    def to_dict(self) -> Dict[str, Any]:
        return {"reservation_id": self.reservation_id,
                "slice_id": self.slice_id,
                "origin": list(self.origin), "shape": list(self.shape),
                "chips": self.chips}


class SliceGrid:
    """Sub-slice allocator for ONE slice. Not thread-safe: the owning
    TopologyView serializes access."""

    def __init__(self, info: SliceInfo):
        self.info = info
        self._used: Dict[Tuple[int, int], str] = {}  # chip -> reservation
        self._reservations: Dict[str, SubSlice] = {}

    @property
    def free_chips(self) -> int:
        return self.info.chips - len(self._used)

    def _fits(self, shape: Tuple[int, int]) -> bool:
        gx, gy = self.info.topology
        return shape[0] <= gx and shape[1] <= gy

    def _orientations(self, shape: Tuple[int, int]
                      ) -> List[Tuple[int, int]]:
        out = [shape]
        if shape[::-1] != shape:
            out.append(shape[::-1])
        return [s for s in out if self._fits(s)]

    def reserve(self, shape: Tuple[int, int],
                owner: str = "") -> Optional[SubSlice]:
        """Reserve an aligned contiguous ``shape`` rectangle; None when
        no aligned free block exists (the caller may try another slice,
        queue, or reject — NEVER assemble a fragment). Origins are
        multiples of the block shape (buddy alignment): frees coalesce
        by construction, so two released 2x2 neighbors are always
        re-reservable as either 2x2 — no compaction pass exists or is
        needed."""
        for sh in self._orientations(shape):
            gx, gy = self.info.topology
            for ox in range(0, gx - sh[0] + 1, sh[0]):
                for oy in range(0, gy - sh[1] + 1, sh[1]):
                    block = [(ox + i, oy + j) for i in range(sh[0])
                             for j in range(sh[1])]
                    if any(c in self._used for c in block):
                        continue
                    rid = f"sub-{next(_reservation_ids)}"
                    sub = SubSlice(rid, self.info.slice_id, (ox, oy), sh)
                    for c in block:
                        self._used[c] = rid
                    self._reservations[rid] = sub
                    return sub
        return None

    def release(self, reservation_id: str) -> bool:
        sub = self._reservations.pop(reservation_id, None)
        if sub is None:
            return False
        for c in sub.chip_ids():
            self._used.pop(c, None)
        return True

    def largest_free_block(self) -> int:
        """Chips in the largest aligned rectangle still reservable: the
        honest capacity signal (free_chips alone overstates a
        checkerboarded slice)."""
        best = 0
        gx, gy = self.info.topology
        for sx in _divisors(gx):
            for sy in _divisors(gy):
                if sx * sy <= best:
                    continue
                probe = [(i, j) for i in range(sx) for j in range(sy)]
                for ox in range(0, gx - sx + 1, sx):
                    for oy in range(0, gy - sy + 1, sy):
                        if all((ox + i, oy + j) not in self._used
                               for i, j in probe):
                            best = max(best, sx * sy)
                            break
                    else:
                        continue
                    break
        return best

    def fragmentation(self) -> float:
        """1 - largest_free_block / free_chips: 0 = all free capacity is
        one contiguous block, 1 = free chips exist but none are
        reservable together."""
        free = self.free_chips
        if free == 0:
            return 0.0
        return round(1.0 - self.largest_free_block() / free, 4)

    def summary(self) -> Dict[str, Any]:
        return {
            "slice_id": self.info.slice_id,
            "topology": list(self.info.topology),
            "chips": self.info.chips,
            "chips_per_host": self.info.chips_per_host,
            "hosts": self.info.hosts,
            "chips_free": self.free_chips,
            "largest_free_block": self.largest_free_block(),
            "fragmentation": self.fragmentation(),
            "reservations": {rid: sub.to_dict()
                             for rid, sub in self._reservations.items()},
        }


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


class TopologyView:
    """Cluster-wide slice registry + sub-slice scheduler (controller
    side). All methods are thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._grids: Dict[str, SliceGrid] = {}
        # slice id -> node ids (hex) advertising it (multi-host slices
        # have one node per TPU-VM host, all advertising the same slice).
        self._nodes: Dict[str, List[str]] = {}
        self._owners: Dict[str, str] = {}  # reservation id -> owner tag
        # Demoted hosts (autopilot taint-host action, or an operator):
        # node hex -> monotonic expiry deadline. A tainted host is a
        # placement PREFERENCE, not a hard exclusion — when every
        # feasible slice is tainted the reservation still succeeds
        # (capacity beats hygiene); taints only reorder choices.
        self._taints: Dict[str, float] = {}

    def register(self, node_hex: str, info: SliceInfo) -> None:
        with self._lock:
            grid = self._grids.get(info.slice_id)
            if grid is None:
                self._grids[info.slice_id] = SliceGrid(info)
            nodes = self._nodes.setdefault(info.slice_id, [])
            if node_hex not in nodes:
                nodes.append(node_hex)

    def node_dead(self, node_hex: str) -> None:
        """Forget a dead node; a slice with no live host left drops with
        its reservations (the owners' replicas died with the hosts)."""
        with self._lock:
            for slice_id in list(self._nodes):
                nodes = self._nodes[slice_id]
                if node_hex in nodes:
                    nodes.remove(node_hex)
                if not nodes:
                    grid = self._grids.pop(slice_id, None)
                    self._nodes.pop(slice_id, None)
                    if grid is not None:
                        for rid in list(grid._reservations):
                            self._owners.pop(rid, None)

    # ------------------------------------------------------------ taints

    def _live_taints(self) -> Dict[str, float]:
        """Prune expired taints; returns node hex -> expiry deadline.
        Caller holds ``_lock``."""
        now = time.monotonic()
        for node in [n for n, exp in self._taints.items() if exp <= now]:
            del self._taints[node]
        return self._taints

    def taint(self, node_hex: str, ttl_s: float) -> None:
        """Demote ``node_hex`` from new placement for ``ttl_s`` seconds.
        Re-tainting extends the deadline (never shortens it)."""
        deadline = time.monotonic() + max(0.0, float(ttl_s))
        with self._lock:
            self._taints[node_hex] = max(
                self._taints.get(node_hex, 0.0), deadline)

    def untaint(self, node_hex: str) -> bool:
        """Lift a taint early (probe-based re-admission, operator
        override). Returns whether a live taint existed."""
        with self._lock:
            self._live_taints()
            return self._taints.pop(node_hex, None) is not None

    def tainted(self) -> Dict[str, float]:
        """Live taints as node hex -> remaining seconds."""
        with self._lock:
            now = time.monotonic()
            return {n: round(exp - now, 3)
                    for n, exp in self._live_taints().items()}

    def reserve(self, owner: str, chips: int = 0,
                shape: Optional[Tuple[int, int]] = None
                ) -> Optional[Dict[str, Any]]:
        """Best-fit sub-slice reservation: the feasible slice with the
        fewest free chips wins (bin-packing keeps big contiguous blocks
        available for big replicas). A request larger than ANY single
        slice — or satisfiable only by combining fragments of several
        slices — returns None: ICI contiguity is a hard constraint, not
        a preference. Tainted hosts demote, they don't exclude: slices
        containing a tainted node sort after clean ones, and the
        returned node list is ordered untainted-first so rank->host
        assignment lands on healthy hosts when any exist."""
        if shape is None:
            shape = most_square(chips)
        shape = (int(shape[0]), int(shape[1]))
        with self._lock:
            taints = self._live_taints()
            order = sorted(self._grids.values(),
                           key=lambda g: (any(n in taints for n in
                                              self._nodes[g.info.slice_id]),
                                          g.free_chips,
                                          g.info.slice_id))
            for grid in order:
                sub = grid.reserve(shape, owner)
                if sub is not None:
                    self._owners[sub.reservation_id] = owner
                    out = sub.to_dict()
                    nodes = list(self._nodes[sub.slice_id])
                    out["nodes"] = ([n for n in nodes if n not in taints]
                                    + [n for n in nodes if n in taints])
                    return out
            return None

    def release(self, reservation_id: str) -> bool:
        with self._lock:
            self._owners.pop(reservation_id, None)
            return any(g.release(reservation_id)
                       for g in self._grids.values())

    def release_owner(self, owner: str) -> int:
        """Release every reservation ``owner`` holds (replica death
        cleanup); returns the count released."""
        with self._lock:
            rids = [rid for rid, o in self._owners.items() if o == owner]
            n = 0
            for rid in rids:
                self._owners.pop(rid, None)
                if any(g.release(rid) for g in self._grids.values()):
                    n += 1
            return n

    def state(self) -> Dict[str, Any]:
        with self._lock:
            now = time.monotonic()
            return {
                "slices": {sid: g.summary()
                           for sid, g in self._grids.items()},
                "nodes": {sid: list(nodes)
                          for sid, nodes in self._nodes.items()},
                "owners": dict(self._owners),
                "taints": {n: round(exp - now, 3)
                           for n, exp in self._live_taints().items()},
            }
