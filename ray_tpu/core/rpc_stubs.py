"""Typed RPC client stubs — GENERATED, do not edit by hand.

Regenerate with ``python -m ray_tpu.analysis --gen-stubs`` whenever a
handler signature changes; ``make lint`` (rpc-stub-drift) and
``make lint-stubs-check`` fail on drift. Each ``<Owner>Stub`` wraps an
RPC client (RpcClient / ReconnectingClient / anything with ``.call``)
and exposes every handler its server registers as a real method —
method names, arities, and the transport ``timeout`` kwarg are checked
by Python itself instead of failing stringly at the peer.

Parameters the handler defaults are declared ``=_UNSET`` and simply
omitted from the wire when not passed, so the SERVER-side default stays
the single source of truth.
"""

from __future__ import annotations

_UNSET = object()


class _StubBase:
    __slots__ = ("_client",)

    def __init__(self, client):
        self._client = client

    def _call(self, method, *args, timeout=_UNSET, **kwargs):
        kwargs = {k: v for k, v in kwargs.items() if v is not _UNSET}
        if timeout is not _UNSET:
            kwargs["timeout"] = timeout
        return self._client.call(method, *args, **kwargs)


class ClientServerStub(_StubBase):
    """Typed stubs for the ClientServer RPC surface (generated)."""

    def client_actor_call(self, sid, actor_key, method, args_frame,
                          num_returns, *, timeout=_UNSET):
        return self._call('client_actor_call', sid, actor_key, method,
                          args_frame, num_returns, timeout=timeout)

    def client_actor_create(self, sid, cls_blob, args_frame, options, *,
                            timeout=_UNSET):
        return self._call('client_actor_create', sid, cls_blob, args_frame,
                          options, timeout=timeout)

    def client_cluster_resources(self, *, timeout=_UNSET):
        return self._call('client_cluster_resources', timeout=timeout)

    def client_connect(self, *, timeout=_UNSET):
        return self._call('client_connect', timeout=timeout)

    def client_disconnect(self, sid, *, timeout=_UNSET):
        return self._call('client_disconnect', sid, timeout=timeout)

    def client_get(self, *args, timeout=_UNSET, **kwargs):
        return self._call('client_get', *args, timeout=timeout, **kwargs)

    def client_get_actor(self, sid, name, *, timeout=_UNSET):
        return self._call('client_get_actor', sid, name, timeout=timeout)

    def client_kill(self, sid, actor_key, no_restart, *, timeout=_UNSET):
        return self._call('client_kill', sid, actor_key, no_restart,
                          timeout=timeout)

    def client_ping(self, sid, *, timeout=_UNSET):
        return self._call('client_ping', sid, timeout=timeout)

    def client_put(self, sid, frame, *, timeout=_UNSET):
        return self._call('client_put', sid, frame, timeout=timeout)

    def client_release(self, sid, ref_ids, *, timeout=_UNSET):
        return self._call('client_release', sid, ref_ids, timeout=timeout)

    def client_task(self, sid, fn_blob, args_frame, options, *,
                    timeout=_UNSET):
        return self._call('client_task', sid, fn_blob, args_frame, options,
                          timeout=timeout)

    def client_wait(self, *args, timeout=_UNSET, **kwargs):
        return self._call('client_wait', *args, timeout=timeout, **kwargs)

    def ping(self, *args, timeout=_UNSET, **kwargs):
        return self._call('ping', *args, timeout=timeout, **kwargs)


class ControllerStub(_StubBase):
    """Typed stubs for the Controller RPC surface (generated)."""

    def autoscaler_state(self, *, timeout=_UNSET):
        return self._call('autoscaler_state', timeout=timeout)

    def cluster_resources(self, *, timeout=_UNSET):
        return self._call('cluster_resources', timeout=timeout)

    def create_placement_group(self, pg_id_bytes, bundles, strategy, *,
                               timeout=_UNSET):
        return self._call('create_placement_group', pg_id_bytes, bundles,
                          strategy, timeout=timeout)

    def epoch_bump(self, name, *, timeout=_UNSET):
        return self._call('epoch_bump', name, timeout=timeout)

    def finish_job(self, job_id, state=_UNSET, *, timeout=_UNSET):
        return self._call('finish_job', job_id, state=state, timeout=timeout)

    def fr_dump(self, max_age_s=_UNSET, *, timeout=_UNSET):
        return self._call('fr_dump', max_age_s=max_age_s, timeout=timeout)

    def get_actor(self, actor_id_bytes, *, timeout=_UNSET):
        return self._call('get_actor', actor_id_bytes, timeout=timeout)

    def get_named_actor(self, name, *, timeout=_UNSET):
        return self._call('get_named_actor', name, timeout=timeout)

    def get_placement_group(self, pg_id_bytes, *, timeout=_UNSET):
        return self._call('get_placement_group', pg_id_bytes, timeout=timeout)

    def heartbeat(self, node_id_bytes, available, queue_len, seq=_UNSET, *,
                  timeout=_UNSET):
        return self._call('heartbeat', node_id_bytes, available, queue_len,
                          seq=seq, timeout=timeout)

    def kill_actor(self, actor_id_bytes, no_restart=_UNSET, *,
                   timeout=_UNSET):
        return self._call('kill_actor', actor_id_bytes, no_restart=no_restart,
                          timeout=timeout)

    def kv_del(self, key, *, timeout=_UNSET):
        return self._call('kv_del', key, timeout=timeout)

    def kv_get(self, key, *, timeout=_UNSET):
        return self._call('kv_get', key, timeout=timeout)

    def kv_keys(self, prefix=_UNSET, *, timeout=_UNSET):
        return self._call('kv_keys', prefix=prefix, timeout=timeout)

    def kv_put(self, key, value, overwrite=_UNSET, *, timeout=_UNSET):
        return self._call('kv_put', key, value, overwrite=overwrite,
                          timeout=timeout)

    def kv_put_fenced(self, key, value, epoch, epoch_name, *, timeout=_UNSET):
        return self._call('kv_put_fenced', key, value, epoch, epoch_name,
                          timeout=timeout)

    def list_actors(self, *, timeout=_UNSET):
        return self._call('list_actors', timeout=timeout)

    def list_jobs(self, *, timeout=_UNSET):
        return self._call('list_jobs', timeout=timeout)

    def list_metrics(self, *, timeout=_UNSET):
        return self._call('list_metrics', timeout=timeout)

    def list_nodes(self, *, timeout=_UNSET):
        return self._call('list_nodes', timeout=timeout)

    def list_task_events(self, limit=_UNSET, *, timeout=_UNSET):
        return self._call('list_task_events', limit=limit, timeout=timeout)

    def metrics_text(self, *, timeout=_UNSET):
        return self._call('metrics_text', timeout=timeout)

    def mh_barrier(self, group_id, name, member, epoch, payload=_UNSET,
                   timeout_s=_UNSET, *, timeout=_UNSET):
        return self._call('mh_barrier', group_id, name, member, epoch,
                          payload=payload, timeout_s=timeout_s,
                          timeout=timeout)

    def mh_drop_group(self, group_id, *, timeout=_UNSET):
        return self._call('mh_drop_group', group_id, timeout=timeout)

    def mh_group_get(self, group_id, key, *, timeout=_UNSET):
        return self._call('mh_group_get', group_id, key, timeout=timeout)

    def mh_group_put(self, group_id, key, value, epoch, *, timeout=_UNSET):
        return self._call('mh_group_put', group_id, key, value, epoch,
                          timeout=timeout)

    def mh_group_state(self, group_id=_UNSET, *, timeout=_UNSET):
        return self._call('mh_group_state', group_id=group_id,
                          timeout=timeout)

    def mh_member_beat(self, group_id, member, epoch, *, timeout=_UNSET):
        return self._call('mh_member_beat', group_id, member, epoch,
                          timeout=timeout)

    def mh_register_group(self, group_id, num_hosts, reservation_id=_UNSET,
                          owner=_UNSET, *, timeout=_UNSET):
        return self._call('mh_register_group', group_id, num_hosts,
                          reservation_id=reservation_id, owner=owner,
                          timeout=timeout)

    def pick_node(self, resources, strategy=_UNSET, caller_node_id=_UNSET,
                  excluded=_UNSET, *, timeout=_UNSET):
        return self._call('pick_node', resources, strategy=strategy,
                          caller_node_id=caller_node_id, excluded=excluded,
                          timeout=timeout)

    def ping(self, *args, timeout=_UNSET, **kwargs):
        return self._call('ping', *args, timeout=timeout, **kwargs)

    def pipe_drop(self, pipeline_id, *, timeout=_UNSET):
        return self._call('pipe_drop', pipeline_id, timeout=timeout)

    def pipe_register(self, pipeline_id, num_stages, group_id=_UNSET,
                      owner=_UNSET, *, timeout=_UNSET):
        return self._call('pipe_register', pipeline_id, num_stages,
                          group_id=group_id, owner=owner, timeout=timeout)

    def pipe_state(self, pipeline_id=_UNSET, *, timeout=_UNSET):
        return self._call('pipe_state', pipeline_id=pipeline_id,
                          timeout=timeout)

    def pipe_step_complete(self, pipeline_id, step, epoch, *, timeout=_UNSET):
        return self._call('pipe_step_complete', pipeline_id, step, epoch,
                          timeout=timeout)

    def psub_drop(self, channel, key, *, timeout=_UNSET):
        return self._call('psub_drop', channel, key, timeout=timeout)

    def psub_keys(self, channel, *, timeout=_UNSET):
        return self._call('psub_keys', channel, timeout=timeout)

    def psub_poll(self, *args, timeout=_UNSET, **kwargs):
        return self._call('psub_poll', *args, timeout=timeout, **kwargs)

    def psub_poll_many(self, *args, timeout=_UNSET, **kwargs):
        return self._call('psub_poll_many', *args, timeout=timeout, **kwargs)

    def psub_publish(self, channel, key, value, min_version=_UNSET,
                     epoch=_UNSET, *, timeout=_UNSET):
        return self._call('psub_publish', channel, key, value,
                          min_version=min_version, epoch=epoch,
                          timeout=timeout)

    def psub_snapshot(self, channel, *, timeout=_UNSET):
        return self._call('psub_snapshot', channel, timeout=timeout)

    def push_metrics(self, source, snapshot, *, timeout=_UNSET):
        return self._call('push_metrics', source, snapshot, timeout=timeout)

    def push_task_events(self, events, *, timeout=_UNSET):
        return self._call('push_task_events', events, timeout=timeout)

    def register_actor(self, actor_id_bytes, info, spec, opts, *,
                       timeout=_UNSET):
        return self._call('register_actor', actor_id_bytes, info, spec, opts,
                          timeout=timeout)

    def register_job(self, job_id, info, *, timeout=_UNSET):
        return self._call('register_job', job_id, info, timeout=timeout)

    def register_node(self, node_id_bytes, addr, resources, labels,
                      slice_info=_UNSET, *, timeout=_UNSET):
        return self._call('register_node', node_id_bytes, addr, resources,
                          labels, slice_info=slice_info, timeout=timeout)

    def release_subslice(self, reservation_id, *, timeout=_UNSET):
        return self._call('release_subslice', reservation_id, timeout=timeout)

    def remove_placement_group(self, pg_id_bytes, *, timeout=_UNSET):
        return self._call('remove_placement_group', pg_id_bytes,
                          timeout=timeout)

    def report_actor_failure(self, actor_id_bytes, reason=_UNSET, *,
                             timeout=_UNSET):
        return self._call('report_actor_failure', actor_id_bytes,
                          reason=reason, timeout=timeout)

    def reserve_subslice(self, owner, chips, shape=_UNSET, *, timeout=_UNSET):
        return self._call('reserve_subslice', owner, chips, shape=shape,
                          timeout=timeout)

    def taint_host(self, node_hex, ttl_s=_UNSET, *, timeout=_UNSET):
        return self._call('taint_host', node_hex, ttl_s=ttl_s,
                          timeout=timeout)

    def taint_state(self, *, timeout=_UNSET):
        return self._call('taint_state', timeout=timeout)

    def topology_state(self, *, timeout=_UNSET):
        return self._call('topology_state', timeout=timeout)

    def unregister_node(self, node_id_bytes, *, timeout=_UNSET):
        return self._call('unregister_node', node_id_bytes, timeout=timeout)

    def untaint_host(self, node_hex, probe=_UNSET, *, timeout=_UNSET):
        return self._call('untaint_host', node_hex, probe=probe,
                          timeout=timeout)


class CoreWorkerStub(_StubBase):
    """Typed stubs for the CoreWorker RPC surface (generated)."""

    def dump_stacks(self, *, timeout=_UNSET):
        return self._call('dump_stacks', timeout=timeout)

    def free_object(self, oid_bytes, *, timeout=_UNSET):
        return self._call('free_object', oid_bytes, timeout=timeout)

    def get_object(self, *args, timeout=_UNSET, **kwargs):
        return self._call('get_object', *args, timeout=timeout, **kwargs)

    def peek_object(self, oid_bytes, *, timeout=_UNSET):
        return self._call('peek_object', oid_bytes, timeout=timeout)

    def ping(self, *args, timeout=_UNSET, **kwargs):
        return self._call('ping', *args, timeout=timeout, **kwargs)

    def profile_cpu(self, duration_s=_UNSET, hz=_UNSET, *, timeout=_UNSET):
        return self._call('profile_cpu', duration_s=duration_s, hz=hz,
                          timeout=timeout)

    def profile_heap(self, top_n=_UNSET, *, timeout=_UNSET):
        return self._call('profile_heap', top_n=top_n, timeout=timeout)

    def profile_heap_stop(self, *, timeout=_UNSET):
        return self._call('profile_heap_stop', timeout=timeout)

    def pull_done(self, oid_bytes, src_key, new_locator, slot_token=_UNSET, *,
                  timeout=_UNSET):
        return self._call('pull_done', oid_bytes, src_key, new_locator,
                          slot_token=slot_token, timeout=timeout)

    def pull_failed(self, oid_bytes, src_key, bad_key, slot_token=_UNSET, *,
                    timeout=_UNSET):
        return self._call('pull_failed', oid_bytes, src_key, bad_key,
                          slot_token=slot_token, timeout=timeout)

    def push_actor_task(self, spec, *, timeout=_UNSET):
        return self._call('push_actor_task', spec, timeout=timeout)

    def push_task(self, spec, *, timeout=_UNSET):
        return self._call('push_task', spec, timeout=timeout)

    def push_task_batch(self, specs, *, timeout=_UNSET):
        return self._call('push_task_batch', specs, timeout=timeout)

    def reconstruct_object(self, oid_bytes, *, timeout=_UNSET):
        return self._call('reconstruct_object', oid_bytes, timeout=timeout)

    def ref_update(self, deltas, *, timeout=_UNSET):
        return self._call('ref_update', deltas, timeout=timeout)

    def shutdown_worker(self, *, timeout=_UNSET):
        return self._call('shutdown_worker', timeout=timeout)

    def start_actor(self, spec, *, timeout=_UNSET):
        return self._call('start_actor', spec, timeout=timeout)

    def stream_item(self, task_id, index, packed, *, timeout=_UNSET):
        return self._call('stream_item', task_id, index, packed,
                          timeout=timeout)

    def wait_object(self, *args, timeout=_UNSET, **kwargs):
        return self._call('wait_object', *args, timeout=timeout, **kwargs)


class NodeStub(_StubBase):
    """Typed stubs for the Node RPC surface (generated)."""

    def create_actor_worker(self, *args, timeout=_UNSET, **kwargs):
        return self._call('create_actor_worker', *args, timeout=timeout,
                          **kwargs)

    def free_shm_object(self, oid_bytes, *, timeout=_UNSET):
        return self._call('free_shm_object', oid_bytes, timeout=timeout)

    def get_info(self, *, timeout=_UNSET):
        return self._call('get_info', timeout=timeout)

    def kill_worker(self, worker_id_bytes, force=_UNSET, reason=_UNSET, *,
                    timeout=_UNSET):
        return self._call('kill_worker', worker_id_bytes, force=force,
                          reason=reason, timeout=timeout)

    def lease_worker(self, *args, timeout=_UNSET, **kwargs):
        return self._call('lease_worker', *args, timeout=timeout, **kwargs)

    def list_workers(self, *, timeout=_UNSET):
        return self._call('list_workers', timeout=timeout)

    def ping(self, *args, timeout=_UNSET, **kwargs):
        return self._call('ping', *args, timeout=timeout, **kwargs)

    def prestart_workers(self, count, *, timeout=_UNSET):
        return self._call('prestart_workers', count, timeout=timeout)

    def read_shm_chunk(self, oid_bytes, offset, length, *, timeout=_UNSET):
        return self._call('read_shm_chunk', oid_bytes, offset, length,
                          timeout=timeout)

    def read_shm_object(self, oid_bytes, *, timeout=_UNSET):
        return self._call('read_shm_object', oid_bytes, timeout=timeout)

    def register_worker(self, worker_id_bytes, addr, *, timeout=_UNSET):
        return self._call('register_worker', worker_id_bytes, addr,
                          timeout=timeout)

    def release_bundle(self, pg_id, index, *, timeout=_UNSET):
        return self._call('release_bundle', pg_id, index, timeout=timeout)

    def reserve_bundle(self, pg_id, index, resources, *, timeout=_UNSET):
        return self._call('reserve_bundle', pg_id, index, resources,
                          timeout=timeout)

    def return_worker(self, worker_id_bytes, resources, bundle=_UNSET,
                      dead=_UNSET, lease_seq=_UNSET, *, timeout=_UNSET):
        return self._call('return_worker', worker_id_bytes, resources,
                          bundle=bundle, dead=dead, lease_seq=lease_seq,
                          timeout=timeout)

    def validate_lease(self, worker_id_bytes, lease_seq, *, timeout=_UNSET):
        return self._call('validate_lease', worker_id_bytes, lease_seq,
                          timeout=timeout)

    def worker_death_cause(self, worker_id_bytes, *, timeout=_UNSET):
        return self._call('worker_death_cause', worker_id_bytes,
                          timeout=timeout)

    def worker_ping(self, worker_id_bytes, tasks_received=_UNSET,
                    active_tasks=_UNSET, actor_started=_UNSET, *,
                    timeout=_UNSET):
        return self._call('worker_ping', worker_id_bytes,
                          tasks_received=tasks_received,
                          active_tasks=active_tasks,
                          actor_started=actor_started, timeout=timeout)
