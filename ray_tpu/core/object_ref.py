"""ObjectRef: a future for a value in the distributed object store.

Analogue of the reference's ``ObjectRef`` (``python/ray/includes/object_ref.pxi``)
with the load-bearing architectural invariant preserved: **ownership**
(reference: SURVEY §1 — the worker that creates a ref by ``.remote()`` or
``put()`` is its owner; it stores the value or knows where it is, and serves
location/value queries). A deserialized ref therefore carries the owner's RPC
address so any process can resolve it without a central directory.
"""

from __future__ import annotations

from typing import Optional, Tuple

from ray_tpu.core.ids import ObjectID

Addr = Tuple[str, int]


class ObjectRef:
    __slots__ = ("id", "owner_addr", "_weakly_referenced", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_addr: Optional[Addr] = None):
        self.id = object_id
        self.owner_addr = tuple(owner_addr) if owner_addr else None

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __reduce__(self):
        return (ObjectRef, (self.id, self.owner_addr))

    def future(self):
        """Return a concurrent.futures.Future resolving to get(self)."""
        from concurrent.futures import Future
        import threading

        from ray_tpu.core import api

        fut: Future = Future()

        def _resolve():
            try:
                fut.set_result(api.get(self))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=_resolve, daemon=True).start()
        return fut

    def __await__(self):
        """Allow ``await ref`` inside async actors (reference:
        ``ObjectRef.__await__`` in ``object_ref.pxi``)."""
        import asyncio

        return asyncio.wrap_future(
            asyncio.get_event_loop().run_in_executor(None, _blocking_get, self)
        ).__await__()


def _blocking_get(ref: "ObjectRef"):
    from ray_tpu.core import api

    return api.get(ref)
