"""ObjectRef: a future for a value in the distributed object store.

Analogue of the reference's ``ObjectRef`` (``python/ray/includes/object_ref.pxi``)
with the load-bearing architectural invariant preserved: **ownership**
(reference: SURVEY §1 — the worker that creates a ref by ``.remote()`` or
``put()`` is its owner; it stores the value or knows where it is, and serves
location/value queries). A deserialized ref therefore carries the owner's RPC
address so any process can resolve it without a central directory.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional, Tuple

from ray_tpu.core.ids import ObjectID

logger = logging.getLogger(__name__)

Addr = Tuple[str, int]


class _RefTracker:
    """Per-process ObjectRef handle tracker (the distributed-ref-counting
    client half; reference: ``src/ray/core_worker/reference_count.h:61``).

    Counts live ``ObjectRef`` instances per (owner, object). When a process's
    count for an object goes 0 -> 1 it reports +1 to the owner; 1 -> 0
    reports -1 (so the owner's count is "number of processes holding
    handles"). Updates are batched and flushed by a daemon thread — the
    owner-side free grace period absorbs the flush latency. On a local
    1 -> 0 for a *borrowed* object the borrower also drops its cached copy,
    releasing the pinned shm view."""

    _instance: Optional["_RefTracker"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        import collections

        self._lock = threading.Lock()
        self._counts: Dict[Tuple[Addr, bytes], int] = {}
        self._dirty: Dict[Addr, Dict[bytes, int]] = {}
        # Decrements from __del__ land here WITHOUT taking any lock: a
        # destructor can fire from the GC in the middle of a thread that
        # already holds self._lock (deque.append is atomic under the GIL).
        self._pending_decs = collections.deque()
        self._send_failures: Dict[Addr, int] = {}
        self._wake = threading.Event()
        # Live-handle gauge published at snapshot time: monotonic growth
        # of this number is the ref-leak signature `ray_tpu doctor`
        # attributes back to the owning process.
        from ray_tpu.util import metrics as um

        um.add_collector(self._collect_metrics)
        self._thread = threading.Thread(
            target=self._flush_loop, name="ref-tracker", daemon=True)
        self._thread.start()

    def _collect_metrics(self) -> None:
        from ray_tpu.core.config import config
        from ray_tpu.core.coremetrics import OBJ_LIVE_REFS

        if config.core_metrics_enabled:
            with self._lock:
                n = len(self._counts)
            OBJ_LIVE_REFS.set(float(n))

    @classmethod
    def get(cls) -> "_RefTracker":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def inc(self, owner: Addr, oid: bytes) -> None:
        apply_local = False
        with self._lock:
            key = (owner, oid)
            n = self._counts.get(key, 0) + 1
            self._counts[key] = n
            if n == 1:
                if self._is_local_owner(owner):
                    # Owner-local +1 applies SYNCHRONOUSLY, not via the
                    # batched flush: under full-suite load the flush
                    # thread can be starved past ref_free_grace_s, and a
                    # borrower's net-zero touch (+1/-1 in one window)
                    # would then arm the owner's zero-clock while our own
                    # +1 still sat in _dirty — the sweeper frees an
                    # object the driver is about to get()
                    # (ObjectFreedError under load). Matching decs stay
                    # batched: they only ever run after this inc.
                    apply_local = True
                else:
                    d = self._dirty.setdefault(owner, {})
                    d[oid] = d.get(oid, 0) + 1
        if apply_local:
            self._apply_local(owner, {oid: 1})

    def dec(self, owner: Addr, oid: bytes) -> None:
        """GC-safe: only enqueues; the flush thread does the bookkeeping.
        Decrements NEVER apply synchronously — a batched -1 only delays
        a free, while a batched +1 can lose a race against the owner's
        grace sweeper (see inc)."""
        self._pending_decs.append((owner, oid))
        self._wake.set()

    @staticmethod
    def _is_local_owner(owner: Addr) -> bool:
        from ray_tpu.core import runtime

        core = runtime._core_worker
        return core is not None and tuple(owner) == tuple(core.addr)

    def _apply_local(self, owner: Addr, deltas: Dict[bytes, int]) -> None:
        """Apply owner-local deltas straight to the store; fall back to
        the batched dirty map if the core vanished mid-flight (shutdown
        between the locked check and this call)."""
        from ray_tpu.core import runtime

        core = runtime._core_worker
        if core is not None and tuple(owner) == tuple(core.addr):
            try:
                core.apply_ref_updates(deltas)
                return
            # store mid-teardown (interpreter exit): falling through to
            # the batched path below is the handling — the flush loop
            # retries or abandons with the owner.
            # graftlint: disable=swallowed-exception
            except Exception:
                pass
        with self._lock:
            d = self._dirty.setdefault(owner, {})
            for oid, delta in deltas.items():
                d[oid] = d.get(oid, 0) + delta

    def _drain_decs(self) -> None:
        while True:
            try:
                owner, oid = self._pending_decs.popleft()
            except IndexError:
                return
            drop_cache = False
            with self._lock:
                key = (owner, oid)
                n = self._counts.get(key, 0) - 1
                if n <= 0:
                    self._counts.pop(key, None)
                    d = self._dirty.setdefault(owner, {})
                    d[oid] = d.get(oid, 0) - 1
                    drop_cache = True
                else:
                    self._counts[key] = n
            if drop_cache:
                self._drop_borrower_cache(owner, oid)

    def _drop_borrower_cache(self, owner: Addr, oid: bytes) -> None:
        from ray_tpu.core import runtime

        core = runtime._core_worker
        if core is None or owner == core.addr:
            return
        try:
            core.store.drop(ObjectID(oid))
        except Exception:
            from ray_tpu.util.ratelimit import log_every

            # Failure leaves a stale borrower-cache entry (memory, not
            # correctness) — but systematic failure means store trouble.
            log_every("object_ref.cache_drop", 60.0, logger,
                      "borrower cache drop failed", exc_info=True)

    def _flush_loop(self) -> None:
        from ray_tpu.core.config import config

        while True:
            self._wake.wait(config.ref_flush_interval_s)
            self._wake.clear()
            self.flush()

    def flush(self) -> None:
        from ray_tpu.core import runtime

        from ray_tpu.core.rpc import RpcConnectError

        self._drain_decs()
        with self._lock:
            dirty, self._dirty = self._dirty, {}
        core = runtime._core_worker
        if core is None:
            return
        # Owner-local deltas apply FIRST: shipping to a remote owner can
        # block ~1 s per dead peer in the dial-retry loop (stale owners
        # from torn-down sessions accumulate under test/driver churn),
        # and the local grace sweeper must never wait behind that — a
        # starved local -1 holds an owned object beyond its lifetime, a
        # starved local +1 was the ObjectFreedError flake.
        owners = sorted(dirty, key=lambda o: o != core.addr)
        for owner in owners:
            deltas = dirty[owner]
            # Net-zero deltas still ship: a ref born and dropped inside one
            # flush window must mark the object as touched-then-released on
            # the owner, or it would never become sweepable.
            if not deltas:
                continue
            try:
                if owner == core.addr:
                    core.apply_ref_updates(deltas)
                else:
                    core.clients.get(owner).notify("ref_update", deltas)
                self._send_failures.pop(owner, None)
            except RpcConnectError:
                # The owner process cannot even be dialed: it is gone,
                # and its objects died with it — abandon the deltas NOW
                # instead of burning a ~1 s dial x 25 retries per dead
                # session (which starved the flush thread and every
                # queued dec behind it).
                self._send_failures.pop(owner, None)
                self._count_abandon()
            except Exception:
                # Transient failure: merge the deltas back for retry; a
                # dropped +1/-1 would silently corrupt the owner's count.
                # After repeated failures the owner is dead — its objects
                # die with it, so the deltas can be abandoned.
                fails = self._send_failures.get(owner, 0) + 1
                self._send_failures[owner] = fails
                if fails <= 25:
                    with self._lock:
                        d = self._dirty.setdefault(owner, {})
                        for oid, delta in deltas.items():
                            d[oid] = d.get(oid, 0) + delta
                else:
                    self._count_abandon()

    @staticmethod
    def _count_abandon() -> None:
        from ray_tpu.core.config import config
        from ray_tpu.core.coremetrics import OBJ_FLUSH_ABANDONED

        if config.core_metrics_enabled:
            OBJ_FLUSH_ABANDONED.inc()


def _tracking_enabled() -> bool:
    from ray_tpu.core.config import config

    return config.ref_counting_enabled


class ObjectRef:
    __slots__ = ("id", "owner_addr", "_tracked", "_weakly_referenced",
                 "__weakref__")

    def __init__(self, object_id: ObjectID, owner_addr: Optional[Addr] = None):
        self.id = object_id
        self.owner_addr = tuple(owner_addr) if owner_addr else None
        self._tracked = False
        if self.owner_addr is not None and _tracking_enabled():
            _RefTracker.get().inc(self.owner_addr, object_id.binary())
            self._tracked = True

    def __del__(self):
        if getattr(self, "_tracked", False):
            try:
                _RefTracker.get().dec(self.owner_addr, self.id.binary())
            except Exception:  # graftlint: disable=swallowed-exception
                # __del__ may run during interpreter teardown, when the
                # tracker (or logging itself) is already dismantled.
                pass

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __reduce__(self):
        from ray_tpu.core import serialization

        serialization.record_serialized_ref(self)
        return (ObjectRef, (self.id, self.owner_addr))

    def future(self):
        """Return a concurrent.futures.Future resolving to get(self)."""
        from concurrent.futures import Future
        import threading

        from ray_tpu.core import api

        fut: Future = Future()

        def _resolve():
            try:
                fut.set_result(api.get(self))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=_resolve, daemon=True).start()
        return fut

    def __await__(self):
        """Allow ``await ref`` inside async actors (reference:
        ``ObjectRef.__await__`` in ``object_ref.pxi``)."""
        import asyncio

        return asyncio.wrap_future(
            asyncio.get_event_loop().run_in_executor(None, _blocking_get, self)
        ).__await__()


def _blocking_get(ref: "ObjectRef"):
    from ray_tpu.core import api

    return api.get(ref)
