"""Public core API: init / remote / get / put / wait / kill.

Analogue of the reference's ``python/ray/_private/worker.py`` module-level API
(``ray.init`` :1225, ``get`` :2562, ``put`` :2688, ``wait`` :2753, ``remote``
:3146). ``init()`` with no address boots an in-process cluster — controller +
one node supervisor — then connects this process as the driver; ``init
(address=...)`` connects to an existing cluster (the multi-node-in-one-machine
test fixture from ``ray_tpu.cluster_utils`` uses this).
"""

from __future__ import annotations

import atexit
import inspect
import os
import uuid
from typing import Any, Dict, Optional, Sequence

from ray_tpu.core.actor import ActorClass
from ray_tpu.core.actor import get_actor as _get_actor_direct
from ray_tpu.core.config import config
from ray_tpu.core.errors import RayTpuError
from ray_tpu.core.ids import NodeID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.remote_function import RemoteFunction
from ray_tpu.core.runtime import (
    CoreWorker,
    get_core_worker,
    is_initialized,  # noqa: F401
    set_core_worker,
)

_local_cluster = None  # (controller, node) started by init()
_config_snapshot = None  # config state to restore on shutdown
_log_streamer = None  # driver-side worker-log echo (log_monitor.LogStreamer)


def init(
    address: Optional[Any] = None,
    num_cpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    labels: Optional[Dict[str, str]] = None,
    _system_config: Optional[Dict[str, Any]] = None,
    ignore_reinit_error: bool = False,
):
    """Start (or connect to) a cluster and attach this process as a driver.

    ``address="ray-tpu://host:port"`` instead connects as a THIN CLIENT to a
    :class:`ray_tpu.client.ClientServer` running inside the cluster — this
    process never joins the cluster and needs one outbound connection only
    (reference: Ray Client, ``util/client/``)."""
    global _local_cluster
    if isinstance(address, str) and address.startswith("ray-tpu://"):
        from ray_tpu import client as client_mod

        client = client_mod.connect(address,
                                    ignore_reinit_error=ignore_reinit_error)
        atexit.register(shutdown)
        return client
    if is_initialized():
        if ignore_reinit_error:
            return get_core_worker()
        raise RayTpuError("ray_tpu.init() called twice; "
                          "pass ignore_reinit_error=True to allow")
    global _config_snapshot
    _config_snapshot = config.snapshot()
    if _system_config:
        config.update(_system_config)

    if address is None:
        from ray_tpu.core.controller import Controller
        from ray_tpu.core.node import Node

        node_resources = dict(resources or {})
        if num_cpus is not None:
            node_resources["CPU"] = float(num_cpus)
        node_resources.setdefault("CPU", float(os.cpu_count() or 1))
        _autodetect_tpu(node_resources, labels := dict(labels or {}))
        controller = Controller()
        node = Node(controller.address, node_resources, labels)
        _local_cluster = (controller, node)
        controller_addr = controller.address
        node_addr, node_id = node.address, node.node_id
    else:
        controller_addr = tuple(address)
        from ray_tpu.core.rpc import RpcClient

        probe = RpcClient(controller_addr)
        nodes = [n for n in probe.call("list_nodes") if n["alive"]]
        probe.close()
        if not nodes:
            raise RayTpuError(f"no alive nodes in cluster at {address}")
        head = nodes[0]
        node_addr = tuple(head["addr"])
        node_id = NodeID.from_hex(head["node_id"])

    core = CoreWorker("driver", controller_addr, node_addr, node_id)
    set_core_worker(core)
    core.controller.call("register_job", uuid.uuid4().hex[:8],
                         {"driver_pid": os.getpid()})
    global _log_streamer
    if config.log_to_driver:
        from ray_tpu.core.log_monitor import LogStreamer

        _log_streamer = LogStreamer(core.controller)
    atexit.register(shutdown)
    return core


def _autodetect_tpu(resources: Dict[str, float], labels: Dict[str, str]) -> None:
    """Detect locally attached TPU chips and expose them as the ``TPU``
    resource (reference: ``_private/accelerators/tpu.py:71``
    TPUAcceleratorManager; here detection is JAX-native)."""
    if "TPU" in resources:
        return
    try:
        from ray_tpu.tpu import detect_chip_count

        chips, pod_type = detect_chip_count()
        if chips:
            resources["TPU"] = float(chips)
            if pod_type:
                labels.setdefault("tpu_pod_type", pod_type)
    except Exception:  # graftlint: disable=swallowed-exception (TPU autodetect probe: absence of TPU metadata is the common case)
        pass


def shutdown() -> None:
    global _local_cluster, _config_snapshot, _log_streamer
    client = _client()
    if client is not None:
        client.disconnect()
        return
    if not is_initialized():
        return
    if _log_streamer is not None:
        # Final drain so prints from the last scan window reach the driver
        # before the cluster goes away.
        try:
            if _local_cluster is not None and \
                    _local_cluster[1].log_monitor is not None:
                _local_cluster[1].log_monitor.scan_once()
            _log_streamer.poll_once(window_s=0.2)
        except Exception:  # graftlint: disable=swallowed-exception (final log drain at shutdown)
            pass
        _log_streamer.stop()
        _log_streamer = None
    try:
        # Local-only usage report (reference phones home; we never do).
        from ray_tpu import usage as _usage

        _usage.write_report()
    except Exception:  # graftlint: disable=swallowed-exception (local usage report is optional)
        pass
    if _config_snapshot is not None:
        # _system_config overrides are scoped to the init()..shutdown() span;
        # restore so a later init() in the same process starts clean.
        config.update(_config_snapshot)
        _config_snapshot = None
    core = get_core_worker()
    set_core_worker(None)
    try:
        core.shutdown()
    except Exception:  # graftlint: disable=swallowed-exception (best-effort core teardown)
        pass
    if _local_cluster is not None:
        controller, node = _local_cluster
        _local_cluster = None
        try:
            node.stop()
        finally:
            controller.stop()
    # Reset per-process caches so a fresh init() starts clean.
    from ray_tpu.core import remote_function as _rf
    from ray_tpu.core import actor as _actor

    _rf._exported_keys.clear()
    _actor._seq_counters.clear()
    _actor._inflight.clear()


def _client():
    """Active thin-client connection, if this process is in client mode."""
    from ray_tpu import client as client_mod

    return client_mod.current_client()


def remote(*args, **options):
    """``@remote`` decorator for functions and classes (reference:
    ``worker.py:3146``)."""

    def decorate(target):
        if _client() is not None:
            from ray_tpu import client as client_mod

            if inspect.isclass(target):
                return client_mod.ClientActorClass(target, options)
            return client_mod.ClientRemoteFunction(target, options)
        if inspect.isclass(target):
            return ActorClass(target, options)
        return RemoteFunction(target, options)

    if len(args) == 1 and callable(args[0]) and not options:
        return decorate(args[0])
    if args:
        raise TypeError("@remote options must be keyword arguments")
    return decorate


def get(refs, timeout: Optional[float] = None):
    client = _client()
    if client is not None:
        return client.get(refs, timeout)
    return get_core_worker().get(refs, timeout)


def put(value: Any) -> ObjectRef:
    client = _client()
    if client is not None:
        return client.put(value)
    return get_core_worker().put(value)


def wait(refs: Sequence[ObjectRef], num_returns: int = 1,
         timeout: Optional[float] = None):
    client = _client()
    if client is not None:
        return client.wait(refs, num_returns, timeout)
    return get_core_worker().wait(refs, num_returns, timeout)


def free(refs) -> None:
    """Eagerly release the object-store entries behind ``refs``
    (reference: ``ray._private.internal_api.free``). Owner-local refs
    free synchronously; remote owners get a best-effort ``free_object``
    notify — an unreachable owner is usually a DEAD owner, whose
    objects already died with it (the ref tracker abandons deltas to
    undialable owners), so the miss is not a leak.

    This is the fast path the serve plane's KV-page handoff uses to
    drop multi-MB page payloads within one engine step of the adopt /
    abort decision, instead of waiting out the distributed ref
    tracker's ``ref_free_grace_s`` sweep."""
    if isinstance(refs, ObjectRef):
        refs = [refs]
    core = get_core_worker()
    for ref in refs:
        if ref is None:
            continue
        if ref.owner_addr in (None, core.addr):
            core.free_object(ref.id)
        else:
            try:
                core.clients.get(ref.owner_addr).notify(
                    "free_object", ref.id.binary())
            except Exception:  # noqa: BLE001 — dead owner == already freed
                from ray_tpu.util.ratelimit import log_every

                log_every("api.free", 30.0, __import__("logging")
                          .getLogger(__name__),
                          "remote free_object notify failed",
                          exc_info=True)


def kill(actor_handle, no_restart: bool = True) -> None:
    client = _client()
    if client is not None:
        from ray_tpu.client import ClientActorHandle

        if isinstance(actor_handle, ClientActorHandle):
            client.kill(actor_handle, no_restart=no_restart)
            return
    actor_handle.kill(no_restart=no_restart)


def cluster_resources() -> Dict[str, float]:
    client = _client()
    if client is not None:
        return client.cluster_resources()
    return get_core_worker().controller.call("cluster_resources")


def get_actor(name: str):
    """Look up a named actor (reference: ``ray.get_actor``)."""
    client = _client()
    if client is not None:
        return client.get_actor(name)
    return _get_actor_direct(name)


def nodes():
    return get_core_worker().controller.call("list_nodes")


def available_resources() -> Dict[str, float]:
    total: Dict[str, float] = {}
    for n in nodes():
        if n["alive"]:
            for k, v in n["available"].items():
                total[k] = total.get(k, 0.0) + v
    return total
