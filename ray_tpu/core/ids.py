"""Unique identifiers for objects, tasks, actors, nodes, jobs and workers.

TPU-native analogue of the reference's ID scheme (reference:
``src/ray/common/id.h`` and ``src/ray/design_docs/id_specification.md``).
We keep the load-bearing property — IDs are fixed-width random byte strings,
cheap to hash, copy and ship over the wire — but drop the reference's
task-index/put-index bit-packing: object identity here is purely random
because ownership metadata travels alongside the ref (see
``ray_tpu.core.object_ref.ObjectRef``).
"""

from __future__ import annotations

import os
import threading

_ID_NBYTES = 16


class BaseID:
    """A fixed-width, immutable, hashable identifier."""

    __slots__ = ("_bytes", "_hash")

    NBYTES = _ID_NBYTES

    def __init__(self, id_bytes: bytes):
        if not isinstance(id_bytes, bytes) or len(id_bytes) != self.NBYTES:
            raise ValueError(
                f"{type(self).__name__} requires {self.NBYTES} bytes, got {id_bytes!r}"
            )
        self._bytes = id_bytes
        self._hash = hash((type(self).__name__, id_bytes))

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.NBYTES))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls.NBYTES)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.NBYTES

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self._bytes.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class ObjectID(BaseID):
    pass


class TaskID(BaseID):
    pass


class ActorID(BaseID):
    pass


class NodeID(BaseID):
    pass


class JobID(BaseID):
    NBYTES = 4


class WorkerID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass


class _Counter:
    """Thread-safe monotonically increasing counter (for seq numbers)."""

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value
