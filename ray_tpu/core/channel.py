"""Mutable shared-memory ring channels for compiled-DAG edges.

Analogue of the reference's experimental mutable plasma objects
(``src/ray/core_worker/experimental_mutable_object_manager.h``) with the
BUFFERED semantics of its shared-memory channels
(``python/ray/experimental/channel/shared_memory_channel.py:169``): a
small ring of fixed-size memory-mapped slots per pipeline edge, each
REWRITTEN in turn instead of allocating new immutable objects — repeated
graph execution becomes allocation-free shared-memory handoff, and the
ring depth (default 3) lets the writer run up to N-1 items ahead of the
reader's ack, overlapping stage compute with transfer (the 1F1B pipeline
case; a 1-deep channel serializes handoff with compute).

Protocol (single writer, single reader, same host):

* header: ``write_seq`` (items written), ``read_ack`` (items consumed),
  ``nslots``, ``slot_capacity``, then per-slot payload lengths — 8-byte
  aligned fields; slot payloads follow at ``HEADER + i * slot_capacity``.
* writer: wait until ``write_seq - read_ack < nslots`` (a slot is free),
  serialize the value straight into slot ``write_seq % nslots``
  (``serialization.build_frame`` — one copy), publish the slot's length
  then ``write_seq + 1``.
* reader: wait until ``write_seq > read_ack``, deserialize zero-copy from
  slot ``read_ack % nslots`` (numpy views point into the slot), and
  ``ack`` AFTER the stage function consumed the value — the writer can't
  overwrite a slot whose item is still being read (the reference's
  writer/reader semaphores), but CAN fill the other slots meanwhile.

Waiting is micro-sleep polling: on one host the uncontended round-trip is
microseconds; a futex-free design keeps the file format trivial and
robust to either side dying (the survivor times out). Payloads larger
than a slot fall back to the RPC push path at the call site
(``dag._PipeStage``), as do cross-node edges.
"""

from __future__ import annotations

import mmap
import os
import struct
import time
from typing import Any, Optional, Tuple

HEADER_SIZE = 64  # one cache line: u64 @ 0/8/16/24 + 4 slot lengths @ 32+
MAX_SLOTS = 4     # slot-length array must fit in the header line


class ChannelTimeout(Exception):
    pass


class ChannelClosed(Exception):
    pass


class MutableChannel:
    """One endpoint of a ring-buffered mutable channel over an mmap'd
    file. ``capacity`` is PER SLOT; the creator fixes ``nslots`` (1-4,
    default ``config.dag_channel_slots``) and the opener reads both from
    the header."""

    def __init__(self, path: str, create: bool = False,
                 capacity: int = 8 << 20, nslots: Optional[int] = None):
        self.path = path
        if create:
            if nslots is None:
                from ray_tpu.core.config import config

                nslots = config.dag_channel_slots
            nslots = max(1, min(MAX_SLOTS, int(nslots)))
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "wb") as f:
                f.truncate(HEADER_SIZE + nslots * capacity)
                f.seek(16)
                f.write(struct.pack("<QQ", nslots, capacity))
            os.rename(tmp, path)
        with open(path, "r+b") as f:
            size = os.fstat(f.fileno()).st_size
            self._map = mmap.mmap(f.fileno(), size)
        self.nslots = struct.unpack_from("<Q", self._map, 16)[0]
        self.capacity = struct.unpack_from("<Q", self._map, 24)[0]
        self._closed = False

    # ------------------------------------------------------------- header

    def _load(self, off: int) -> int:
        return struct.unpack_from("<Q", self._map, off)[0]

    def _store(self, off: int, value: int) -> None:
        struct.pack_into("<Q", self._map, off, value)

    @property
    def write_seq(self) -> int:
        return self._load(0)

    @property
    def read_ack(self) -> int:
        return self._load(8)

    # ------------------------------------------------------------- writer

    def write(self, value: Any,
              timeout: Optional[float] = 60.0) -> bool:
        """Serialize ``value`` into the slot; returns False when it does
        not fit (caller falls back to RPC). Blocks while the previous item
        is unconsumed."""
        from ray_tpu.core import serialization

        total, write_fn = serialization.build_frame(value)
        if total > self.capacity:
            return False
        self.write_frame(total, write_fn, timeout)
        return True

    def write_frame(self, total: int, write_fn,
                    timeout: Optional[float] = 60.0) -> None:
        """Low-level write of an already-built frame (callers that must
        size-check before committing — the DAG stage builds the frame
        ONCE and reuses it for the RPC fallback when it doesn't fit).
        ``timeout=None`` waits indefinitely: a full ring is backpressure
        from a slow consumer, not a failure — only ``close()`` (teardown)
        breaks the wait."""
        self._wait(lambda: self.write_seq - self.read_ack < self.nslots,
                   timeout, "reader fell a full ring behind")
        slot = self.write_seq % self.nslots
        off = HEADER_SIZE + slot * self.capacity
        write_fn(memoryview(self._map)[off:off + total])
        self._store(32 + 8 * slot, total)
        # Publish AFTER the payload lands (x86 TSO keeps store order
        # visible across processes).
        self._store(0, self.write_seq + 1)

    # ------------------------------------------------------------- reader

    def read(self, timeout: float = 60.0) -> memoryview:
        """Wait for the next item; returns a zero-copy view of its slot.
        The caller MUST ``ack()`` when done with the view (and anything
        deserialized from it) — until then the writer cannot reuse THIS
        slot (it may still fill the ring's other slots)."""
        self._wait(lambda: self.write_seq > self.read_ack, timeout,
                   "no item arrived")
        slot = self.read_ack % self.nslots
        length = self._load(32 + 8 * slot)
        off = HEADER_SIZE + slot * self.capacity
        return memoryview(self._map)[off:off + length]

    def ack(self) -> None:
        self._store(8, self.read_ack + 1)

    # ------------------------------------------------------------ plumbing

    def _wait(self, cond, timeout: float, what: str) -> None:
        """Micro-sleep polling, NO hot spin: a Python spin loop holds the
        GIL and (on small hosts) the only core, starving the very peer it
        is waiting for — measured 2x slower end-to-end than sleeping."""
        try:
            if cond():
                return
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            while not cond():
                if self._closed:
                    raise ChannelClosed(self.path)
                if deadline is not None and time.monotonic() > deadline:
                    raise ChannelTimeout(f"{self.path}: {what}")
                time.sleep(0.0002)
        except ValueError as e:  # mmap closed mid-wait (teardown race)
            raise ChannelClosed(self.path) from e

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self._map.close()
            except (BufferError, ValueError):
                pass  # exported views still alive; the map dies with us

    def unlink(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass


def channel_path(channel_id: str) -> str:
    """Deterministic path both endpoints derive (same host)."""
    from ray_tpu.core.config import config

    d = os.path.join(config.object_store_fallback_dir, "ray_tpu",
                     "channels")
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f"{channel_id}.chan")
