"""Core-plane instruments + the one cluster summary the surfaces share.

The serve plane got its SLO instruments in ``serve/metrics.py``; this is
the same pattern for the runtime UNDERNEATH it — the PR 1 non-blocking
RPC write path, the object plane, core pubsub, and the controller's
scheduler/heartbeat loops. A stalled peer filling its outbound queue, a
reconnect storm against a dead address, pubsub subscribers falling
versions behind, or monotonic live-ref growth were all invisible until
they became a hang; these instruments make each one a number a fleet
operator (and ``ray_tpu doctor``) can read.

Cost discipline (stricter than serve's per-request rule, because the
RPC reactor is hotter than any request path): hot paths touch **plain
attribute counters under locks they already hold** — never the registry
lock. Snapshot-time collectors (``util.metrics.add_collector``) publish
those counters as gauges / counter-deltas / batched histogram
observations only when a snapshot is actually pushed (heartbeat
cadence). Client-side paths that already pay a syscall (dialing,
object transfer chunks) record directly. Everything gates on
``config.core_metrics_enabled`` (``make bench-obs`` measures the
on-vs-off delta; bar <2% on the RPC microbench and the decode step
loop).

Read the cluster view back through :func:`core_summary` — the single
aggregation behind ``ray_tpu metrics``, the dashboard's core panel and
the doctor's healthy-cluster baseline, exactly as
``serve.metrics.slo_summary`` backs the serve surfaces.
"""

from __future__ import annotations

from typing import Any, Dict, List

from ray_tpu.util.metrics import (Counter, Gauge, Histogram, counter_totals,
                                  gauge_totals, histogram_summary,
                                  merge_histograms)

# Sub-ms grid: reactor flushes are syscall-scale; anything in the tail
# buckets means the kernel buffer (or chaos pacing) pushed back.
_FLUSH_BUCKETS = (0.00001, 0.00005, 0.0001, 0.0005, 0.001, 0.005, 0.01,
                  0.05, 0.1, 0.5)
# Object put/get spans inline-store hits (us) through chunked
# cross-node pulls (seconds).
_OBJ_BUCKETS = (0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0,
                5.0, 30.0)
# Heartbeat RTTs are ~ms on a healthy localhost control plane; the
# upper buckets exist to make outliers (doctor signature) resolvable.
_RTT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5)
# Pubsub versions-behind grid (a count, not a latency).
_LAG_BUCKETS = (1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 500.0)

# ------------------------------------------------------------ RPC plane

RPC_TX_FRAMES = Counter(
    "rpc_tx_frames_total",
    "Reply frames enqueued on server outbound queues.",
    tag_keys=("server",))
RPC_TX_BYTES = Counter(
    "rpc_tx_bytes_total",
    "Reply bytes (incl. frame headers) enqueued on server outbound "
    "queues.", tag_keys=("server",))
RPC_BACKPRESSURE_DROPS = Counter(
    "rpc_backpressure_drops_total",
    "Connections dropped because their outbound queue hit "
    "rpc_outbound_cap_bytes (the peer stopped reading).",
    tag_keys=("server",))
RPC_CONN_DROPS = Counter(
    "rpc_conn_drops_total",
    "Server connection teardowns through _drop (EOF, read/flush error, "
    "over-cap).", tag_keys=("server",))
RPC_OUT_QUEUE_BYTES = Gauge(
    "rpc_outbound_queue_bytes",
    "Bytes currently queued for send across a server's live "
    "connections (snapshot-time sample).", tag_keys=("server",))
RPC_OUT_QUEUE_CONNS = Gauge(
    "rpc_outbound_queue_conns",
    "Live connections with a non-empty outbound queue.",
    tag_keys=("server",))
RPC_FLUSH_S = Histogram(
    "rpc_flush_s",
    "Reactor-side flush latency (one _flush pass; bounded sample ring, "
    "published at snapshot time).",
    boundaries=_FLUSH_BUCKETS, tag_keys=("server",))
RPC_DIALS = Counter(
    "rpc_dials_total",
    "Successful outbound dials, by peer role (controller | peer).",
    tag_keys=("role",))
RPC_DIAL_FAILURES = Counter(
    "rpc_dial_failures_total",
    "Failed TCP connect attempts (each retry counts — a dead address "
    "under active redial shows as a storm).", tag_keys=("role",))
RPC_RECONNECT_RETRIES = Counter(
    "rpc_reconnect_retries_total",
    "ReconnectingClient call retries after a transport failure "
    "(controller restarts / head blips).", tag_keys=("role",))

# --------------------------------------------------------- object plane

OBJ_PUT_BYTES = Counter(
    "obj_put_bytes_total", "Serialized bytes stored by put().")
OBJ_PUT_S = Histogram(
    "obj_put_s", "put() latency: serialize + store (shm or inline).",
    boundaries=_OBJ_BUCKETS)
OBJ_GET_S = Histogram(
    "obj_get_s",
    "get() latency per ref, by resolution path (local | remote).",
    boundaries=_OBJ_BUCKETS, tag_keys=("path",))
OBJ_TRANSFER_BYTES = Counter(
    "obj_transfer_bytes_total",
    "Bytes pulled over the network (chunked node-to-node reads).")
OBJ_LIVE_REFS = Gauge(
    "obj_live_refs",
    "Live ObjectRef handles tracked by this process (monotonic growth "
    "here is the leak signature ray_tpu doctor looks for).")
OBJ_STORE_ENTRIES = Gauge(
    "obj_store_entries", "Entries in this process's in-process store.")
OBJ_STORE_BYTES = Gauge(
    "obj_store_bytes",
    "Serialized bytes held inline by this process's in-process store "
    "(shm-resident values are counted by the node store, not here).")
OBJ_FLUSH_ABANDONED = Counter(
    "obj_ref_flush_abandoned_total",
    "Ref-count delta batches abandoned because their owner process "
    "could not be dialed (owner gone — its objects died with it).")

# --------------------------------------------------------- pubsub plane

PSUB_PUBLISHES = Counter(
    "psub_publishes_total", "Hub publishes, by channel.",
    tag_keys=("channel",))
PSUB_DELIVER_S = Histogram(
    "psub_deliver_s",
    "publish -> long-poll delivery latency for subscribers that were "
    "parked when the publish landed.",
    boundaries=_FLUSH_BUCKETS, tag_keys=("channel",))
PSUB_SUB_LAG = Histogram(
    "psub_sub_lag",
    "Versions a subscriber skipped per successful poll (1 = fully "
    "caught up; growth means consumers can't keep up with publishes).",
    boundaries=_LAG_BUCKETS, tag_keys=("channel",))
PSUB_DROPPED_NOTIFIES = Counter(
    "psub_dropped_notifies_total",
    "Subscriber-side watch deliveries dropped (callback raised or the "
    "poll RPC failed).", tag_keys=("channel",))

# -------------------------------------------------------- control plane

CTRL_HEARTBEATS = Counter(
    "ctrl_heartbeats_total", "Heartbeats applied by the controller.")
CTRL_PENDING_DEMAND = Gauge(
    "ctrl_pending_demand",
    "Live unmet scheduling-demand shapes (autoscaler signal).")
CTRL_NODE_DEATHS = Counter(
    "ctrl_node_deaths_total",
    "Nodes declared dead (missed heartbeats or unregister).")
CTRL_SCHEDULE_S = Histogram(
    "ctrl_actor_schedule_s",
    "Actor lease-grant latency: placement pick -> worker leased -> "
    "__init__ pushed -> ALIVE.", boundaries=_OBJ_BUCKETS)
NODE_HEARTBEAT_RTT = Histogram(
    "node_heartbeat_rtt_s",
    "Node-observed heartbeat round-trip to the controller; one series "
    "per node.", boundaries=_RTT_BUCKETS, tag_keys=("node",))

# ------------------------------------------------------ multihost plane
#
# Host-group gangs (core/multihost.py). Barrier waits span instant
# rendezvous (everyone already arrived) through straggler-bound stalls;
# the entered/absent split per member is what `ray_tpu doctor`'s
# gang-hang signature reads.

_BARRIER_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                    30.0, 60.0)

MH_GROUPS = Gauge(
    "mh_groups",
    "Host groups currently registered with the controller's group "
    "registry.")
MH_MEMBER_EPOCH = Gauge(
    "mh_member_epoch",
    "Group epoch each gang member last heartbeat under; a member "
    "pinned below its group's current epoch is a fenced zombie.",
    tag_keys=("group", "member"))
MH_BARRIER_ENTERED = Gauge(
    "mh_barrier_entered",
    "1 when the member has arrived at a currently-pending group "
    "barrier, 0 when the gang is waiting on it (uniform 0 when no "
    "barrier is pending). Persistent divergence is the gang-hang "
    "signature.", tag_keys=("group", "member"))
MH_BARRIER_WAIT_S = Histogram(
    "mh_barrier_wait_s",
    "Time a member parked in a group rendezvous barrier before "
    "completion or timeout.", boundaries=_BARRIER_BUCKETS)

# ------------------------------------------------------ pipeline plane
#
# MPMD pipeline training (train/pipeline_plane.py). The driver-side
# scheduler owns these series (it sees every dispatch and completion,
# including the ones a stalled stage never answers): the per-stage idle
# split is what `ray_tpu doctor`'s pipeline-stall signature reads — a
# straggler stage is BUSY (idle ~0) while every stage starved behind it
# idles for the whole window.

# Descriptor sizes: stage RPCs must carry refs + metadata, never
# tensors; anything near the top buckets means activation bytes leaked
# into the control path (tests pin the p99 against the budget).
_DESC_BUCKETS = (128.0, 256.0, 512.0, 1024.0, 2048.0, 4096.0, 8192.0,
                 16384.0)

PIPE_STAGE_IDLE_S = Gauge(
    "pipeline_stage_idle_s",
    "Seconds each pipeline stage has been idle (no dispatched work), "
    "as seen by the driver-side scheduler; 0 while a call is in "
    "flight. One stage busy while the rest idle for a whole doctor "
    "window is the pipeline-stall signature.",
    tag_keys=("pipeline", "stage"))
PIPE_ACTIVATION_BYTES = Gauge(
    "pipeline_activation_bytes",
    "Bytes of activation/gradient tensors currently in flight through "
    "the object plane for a pipeline (driver ref-ledger accounting; "
    "returns to 0 between steps).", tag_keys=("pipeline",))
PIPE_INFLIGHT = Gauge(
    "pipeline_inflight_microbatches",
    "Microbatches admitted but not yet fully backpropagated (the 1F1B "
    "in-flight window actually in use).", tag_keys=("pipeline",))
PIPE_DESC_BYTES = Histogram(
    "pipeline_desc_bytes",
    "Serialized stage-RPC descriptor size (ref + metadata, never "
    "tensor bytes — the tensors ride the object plane).",
    boundaries=_DESC_BUCKETS, tag_keys=("pipeline",))
PIPE_STEP_PHASE_S = Gauge(
    "pipeline_step_breakdown_s",
    "Stage-seconds of the last completed optimizer step by phase "
    "(fwd | bwd | apply | allgather | idle): fwd/bwd sum the driver-"
    "observed dispatch->reply occupancy, apply charges the concurrent "
    "update fan-out to every stage, idle is the remainder of "
    "stages x step wall — the measured 1F1B bubble. The TPU MFU "
    "accounting discipline: every stage-second of a step has a row.",
    tag_keys=("pipeline", "phase"))
PIPE_MODEL_TFLOPS = Gauge(
    "pipeline_model_tflops",
    "Achieved model TFLOP/s of the last completed step "
    "(~8 x params x tokens / wall: 2 fwd + 4 bwd + 2 recompute-fwd — "
    "stage backwards recompute their forward inside jax.vjp).",
    tag_keys=("pipeline",))
PIPE_MFU = Gauge(
    "pipeline_mfu_pct",
    "Model FLOPs utilization estimate: achieved model TFLOP/s over "
    "the gang's configured peak (config.pipe_peak_tflops) x 100. "
    "Absent unless the peak is configured — there is no honest peak "
    "for a time-sliced CPU host.", tag_keys=("pipeline",))

# --------------------------------------------------- autopilot plane
# Closed-loop remediation (autopilot.py): every decision the
# reconciler takes — or declines — has a series. actions_total's
# outcome label distinguishes applied / dry-run / stale-epoch /
# failed; suppressed_total's reason label is WHY nothing happened
# (kill-switch, hysteresis, rate-limit). Both label sets are fixed
# small enums — never ids.

AUTOPILOT_ACTIONS = Counter(
    "autopilot_actions_total",
    "Remediation actions the autopilot decided, by action class "
    "(taint-host | reschedule-gang | shed-tenant | resize-deployment) "
    "and outcome (applied | dry-run | stale-epoch | failed). "
    "stale-epoch is the fence working: the cluster self-healed "
    "between observation and action, so the action no-opped.",
    tag_keys=("action", "outcome"))
AUTOPILOT_SUPPRESSED = Counter(
    "autopilot_suppressed_total",
    "Remediations the autopilot declined, by reason (disabled | "
    "hysteresis | rate-limit). Hysteresis suppressions on a healthy "
    "cluster are the false-remediation guard doing its job.",
    tag_keys=("reason",))
AUTOPILOT_MTTR_S = Gauge(
    "autopilot_mttr_s",
    "Seconds from a signature's FIRST observation to its remediation "
    "action being applied (per action class; last action wins). The "
    "detect->decide->act latency of the closed loop — hysteresis "
    "windows are inside it by design.",
    tag_keys=("action",))


# ----------------------------------------------------- cluster summary


def _tag_map(totals: Dict[tuple, float], tag: str) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for key, v in totals.items():
        label = dict(key).get(tag, "-")
        out[label] = out.get(label, 0.0) + v
    return out


def _merged_summary(aggregated, name: str, tag: str = None
                    ) -> Dict[str, Any]:
    merged = merge_histograms(aggregated, name)
    if tag is None:
        total = None
        for entry in merged.values():
            if total is None:
                total = dict(entry)
            else:
                total["counts"] = [a + b for a, b in
                                   zip(total["counts"], entry["counts"])]
                total["sum"] += entry["sum"]
                total["count"] += entry["count"]
        return histogram_summary(total) if total else {}
    return {dict(k).get(tag, "-"): histogram_summary(e)
            for k, e in merged.items()}


def core_summary(aggregated: Dict[str, List[Dict[str, Any]]]
                 ) -> Dict[str, Any]:
    """Cluster-wide core-plane view from the controller's aggregated
    metrics (``list_metrics``): the single read path behind
    ``ray_tpu metrics``, the dashboard core panel, and the doctor's
    evidence rendering."""
    out: Dict[str, Any] = {}
    out["rpc"] = {
        "tx_frames": sum(counter_totals(aggregated,
                                        "rpc_tx_frames_total").values()),
        "tx_bytes": sum(counter_totals(aggregated,
                                       "rpc_tx_bytes_total").values()),
        "backpressure_drops": sum(counter_totals(
            aggregated, "rpc_backpressure_drops_total").values()),
        "conn_drops": sum(counter_totals(
            aggregated, "rpc_conn_drops_total").values()),
        "queue_bytes": sum(gauge_totals(
            aggregated, "rpc_outbound_queue_bytes").values()),
        "queued_conns": sum(gauge_totals(
            aggregated, "rpc_outbound_queue_conns").values()),
        "dials": _tag_map(counter_totals(aggregated, "rpc_dials_total"),
                          "role"),
        "dial_failures": _tag_map(
            counter_totals(aggregated, "rpc_dial_failures_total"), "role"),
        "reconnect_retries": sum(counter_totals(
            aggregated, "rpc_reconnect_retries_total").values()),
        "flush_s": _merged_summary(aggregated, "rpc_flush_s"),
    }
    out["objects"] = {
        "put_bytes": sum(counter_totals(aggregated,
                                        "obj_put_bytes_total").values()),
        "transfer_bytes": sum(counter_totals(
            aggregated, "obj_transfer_bytes_total").values()),
        "live_refs": sum(gauge_totals(aggregated, "obj_live_refs").values()),
        "store_entries": sum(gauge_totals(
            aggregated, "obj_store_entries").values()),
        "store_bytes": sum(gauge_totals(
            aggregated, "obj_store_bytes").values()),
        "flush_abandoned": sum(counter_totals(
            aggregated, "obj_ref_flush_abandoned_total").values()),
        "put_s": _merged_summary(aggregated, "obj_put_s"),
        "get_s": _merged_summary(aggregated, "obj_get_s", tag="path"),
    }
    out["pubsub"] = {
        "publishes": _tag_map(counter_totals(
            aggregated, "psub_publishes_total"), "channel"),
        "dropped_notifies": sum(counter_totals(
            aggregated, "psub_dropped_notifies_total").values()),
        "deliver_s": _merged_summary(aggregated, "psub_deliver_s"),
        "sub_lag": _merged_summary(aggregated, "psub_sub_lag",
                                   tag="channel"),
    }
    out["control"] = {
        "heartbeats": sum(counter_totals(
            aggregated, "ctrl_heartbeats_total").values()),
        "pending_demand": sum(gauge_totals(
            aggregated, "ctrl_pending_demand").values()),
        "node_deaths": sum(counter_totals(
            aggregated, "ctrl_node_deaths_total").values()),
        "actor_schedule_s": _merged_summary(aggregated,
                                            "ctrl_actor_schedule_s"),
        "heartbeat_rtt_s": _merged_summary(aggregated,
                                           "node_heartbeat_rtt_s",
                                           tag="node"),
        "pending_subslice_releases": sum(gauge_totals(
            aggregated, "serve_pending_subslice_releases").values()),
    }
    out["multihost"] = {
        "groups": sum(gauge_totals(aggregated, "mh_groups").values()),
        "member_series": len(gauge_totals(aggregated,
                                          "mh_member_epoch")),
        "barrier_wait_s": _merged_summary(aggregated,
                                          "mh_barrier_wait_s"),
    }
    out["pipeline"] = {
        "inflight_microbatches": sum(gauge_totals(
            aggregated, "pipeline_inflight_microbatches").values()),
        "activation_bytes": sum(gauge_totals(
            aggregated, "pipeline_activation_bytes").values()),
        "stage_idle_s": _tag_map(gauge_totals(
            aggregated, "pipeline_stage_idle_s"), "stage"),
        "desc_bytes": _merged_summary(aggregated, "pipeline_desc_bytes"),
        "step_breakdown_s": _tag_map(gauge_totals(
            aggregated, "pipeline_step_breakdown_s"), "phase"),
        "model_tflops": _tag_map(gauge_totals(
            aggregated, "pipeline_model_tflops"), "pipeline"),
        "mfu_pct": _tag_map(gauge_totals(
            aggregated, "pipeline_mfu_pct"), "pipeline"),
    }
    out["autopilot"] = {
        "actions": _tag_map(counter_totals(
            aggregated, "autopilot_actions_total"), "action"),
        "outcomes": _tag_map(counter_totals(
            aggregated, "autopilot_actions_total"), "outcome"),
        "suppressed": _tag_map(counter_totals(
            aggregated, "autopilot_suppressed_total"), "reason"),
        "mttr_s": _tag_map(gauge_totals(
            aggregated, "autopilot_mttr_s"), "action"),
    }
    return out
