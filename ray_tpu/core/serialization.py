"""Object serialization with zero-copy buffer framing.

Analogue of the reference's serialization stack
(``python/ray/_private/serialization.py`` + the cloudpickle fork +
pickle-protocol-5 out-of-band buffers): values are pickled with
``protocol=5`` and a ``buffer_callback`` so large contiguous payloads
(numpy arrays, and therefore host-staged ``jax.Array`` data) are captured as
separate buffers rather than copied into the pickle stream. The framed layout
below is what lands in the shared-memory object store; deserialization builds
numpy arrays that *view* the store's mmap directly (zero-copy), which is the
TPU equivalent of plasma's zero-copy reads — host RAM is the staging bus for
TPU infeed, so avoiding host copies is what matters.

Frame layout (little-endian u64s, buffers 64-byte aligned for TPU-friendly
host staging and safe numpy views)::

    u64 npickle | u64 nbuf | (u64 offset, u64 len) * nbuf | pickle | pad | buf0 | pad | buf1 ...
"""

from __future__ import annotations

import contextlib
import pickle
import struct
import threading
from typing import Any, List, Tuple

import cloudpickle

_ALIGN = 64
_U64 = struct.Struct("<Q")

# Nested-ObjectRef capture: while a capture is active on this thread, every
# ObjectRef pickled (at any nesting depth) is recorded. The runtime pins
# those refs for as long as the serialized frame is alive, so an object
# reachable only through a stored/in-flight frame can't be freed (reference:
# ReferenceCounter tracking refs found at serialization time,
# reference_count.h:61).
_capture = threading.local()


@contextlib.contextmanager
def capture_refs():
    prev = getattr(_capture, "refs", None)
    _capture.refs = []
    try:
        yield _capture.refs
    finally:
        _capture.refs = prev


def record_serialized_ref(ref) -> None:
    refs = getattr(_capture, "refs", None)
    if refs is not None:
        refs.append(ref)


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


def build_frame(value: Any):
    """Pickle ``value`` (protocol 5, out-of-band buffers) and compute the
    frame layout WITHOUT materializing it. Returns ``(total_size, write)``
    where ``write(buf)`` fills any writable buffer of ``total_size`` bytes —
    letting callers serialize straight into the shared-memory store with one
    copy instead of three (build bytearray -> bytes() -> shm memcpy)."""
    buffers: List[pickle.PickleBuffer] = []
    try:
        payload = pickle.dumps(value, protocol=5, buffer_callback=buffers.append)
    except Exception:
        buffers = []
        payload = cloudpickle.dumps(value, protocol=5,
                                    buffer_callback=buffers.append)
    raws = [b.raw() for b in buffers]
    header_size = 16 + 16 * len(raws)
    # Compute aligned offsets (relative to frame start).
    cursor = _align(header_size + len(payload))
    offsets: List[Tuple[int, int]] = []
    for raw in raws:
        offsets.append((cursor, raw.nbytes))
        cursor = _align(cursor + raw.nbytes)
    total = cursor if raws else header_size + len(payload)

    def write(out) -> None:
        out[0:8] = _U64.pack(len(payload))
        out[8:16] = _U64.pack(len(raws))
        pos = 16
        for off, ln in offsets:
            out[pos:pos + 8] = _U64.pack(off)
            out[pos + 8:pos + 16] = _U64.pack(ln)
            pos += 16
        out[pos:pos + len(payload)] = payload
        for raw, (off, ln) in zip(raws, offsets):
            out[off:off + ln] = raw

    return total, write


def serialize(value: Any) -> bytes:
    """Serialize ``value`` to the framed zero-copy layout."""
    total, write = build_frame(value)
    out = bytearray(total)
    write(out)
    return bytes(out)


def serialized_size(value: Any) -> int:
    """Size the framed serialization of ``value`` would occupy (by building it)."""
    return len(serialize(value))


def deserialize(frame) -> Any:
    """Deserialize a frame produced by :func:`serialize`.

    ``frame`` may be ``bytes`` or a ``memoryview`` over shared memory; in the
    latter case out-of-band buffers are zero-copy views into it.
    """
    view = memoryview(frame)
    npickle = _U64.unpack(view[0:8])[0]
    nbuf = _U64.unpack(view[8:16])[0]
    pos = 16
    bufs = []
    for _ in range(nbuf):
        off = _U64.unpack(view[pos:pos + 8])[0]
        ln = _U64.unpack(view[pos + 8:pos + 16])[0]
        bufs.append(view[off:off + ln])
        pos += 16
    payload = view[pos:pos + npickle]
    return pickle.loads(payload, buffers=bufs)


def dumps_function(fn) -> bytes:
    """Pickle a function/class for shipping to workers (cloudpickle: handles
    ``__main__``, closures, lambdas by value; importable modules by reference,
    resolvable on workers because the driver's ``sys.path`` is propagated —
    reference: ``python/ray/_private/function_manager.py``)."""
    return cloudpickle.dumps(fn, protocol=5)


def loads_function(blob: bytes):
    return pickle.loads(blob)
