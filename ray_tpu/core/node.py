"""Node supervisor: per-node scheduler daemon + worker pool (raylet equivalent).

Analogue of the reference's raylet (``src/ray/raylet/node_manager.h:119`` +
``worker_pool.h:159``): grants *worker leases* against the node's resource
pool (the local half of the two-level scheduler — cluster-level node selection
lives in the controller), forks and pools Python worker processes, reaps idle
and dead workers, reserves placement-group bundles (the node half of the 2PC
in ``placement_group_resource_manager.h``), and gossips its available
resources to the controller via heartbeats (standing in for the reference's
``RaySyncer`` resource-view stream, ``ray_syncer.h:88``).

Lease protocol (reference: ``node_manager.proto`` RequestWorkerLease /
ReturnWorker): a caller leases a worker, pushes task specs to it directly
(owner->worker, like the reference's direct task transport), and returns the
lease when its pipeline for that scheduling key drains. Leases block FIFO-ish
on the resource condition variable; ``return_worker`` and bundle ops are
inline RPC methods so they always make progress while lease calls wait.
"""

from __future__ import annotations

import logging
import os
import pickle
import select
import signal
import struct
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import resources as resmath
from ray_tpu.core.config import config
from ray_tpu.core.ids import NodeID, WorkerID
from ray_tpu.core.rpc import ClientPool, ReconnectingClient, RpcServer
from ray_tpu.util.ratelimit import log_every

logger = logging.getLogger(__name__)

Addr = Tuple[str, int]
BundleKey = Tuple[bytes, int]  # (placement group id, bundle index)


def shm_store_path(node_id: NodeID) -> str:
    """Deterministic store-file path for a node (all processes derive it)."""
    return os.path.join(config.object_store_fallback_dir, "ray_tpu",
                        f"{node_id.hex()}.store")


def spill_dir(node_id: NodeID) -> str:
    """Per-node directory for objects spilled to disk when the shm store is
    full (reference: ``local_object_manager.h:110`` spill-to-filesystem; one
    dir per node keeps the multi-node-in-one-machine fixture honest)."""
    return os.path.join(config.object_spill_dir, node_id.hex())


def spill_file(node_id: NodeID, oid_bytes: bytes) -> str:
    return os.path.join(spill_dir(node_id), oid_bytes.hex() + ".bin")


def _runtime_env_hash(runtime_env: Optional[Dict[str, Any]]) -> str:
    if not runtime_env:
        return ""
    import hashlib
    import json

    return hashlib.sha1(
        json.dumps(runtime_env, sort_keys=True, default=str).encode()
    ).hexdigest()[:16]


def _kill_and_reap(proc: subprocess.Popen, force: bool) -> None:
    """Kill a worker process and reap it so no zombie lingers in the
    (long-lived) driver process hosting this node supervisor."""
    try:
        if force:
            proc.kill()
        else:
            proc.terminate()
    except OSError:
        pass
    try:
        proc.wait(timeout=5.0)
    except (subprocess.TimeoutExpired, OSError):
        pass


class _ForkserverError(Exception):
    """Template process unavailable/failed — callers fall back to spawn."""


class _PendingProc:
    """Placeholder proc while a forkserver child's pid reply is in flight.
    The handle must already be in the worker table (the warm child can hit
    ``register_worker`` within ms of ``os.fork``), and the reaper may look
    at it before the real ``_ForkedProc`` is swapped in."""

    pid = -1
    returncode: Optional[int] = None

    def poll(self) -> Optional[int]:
        return None

    def terminate(self) -> None:
        pass

    def kill(self) -> None:
        pass

    def wait(self, timeout: Optional[float] = None) -> int:
        raise subprocess.TimeoutExpired("pending-forked-worker", timeout or 0)


class _ForkedProc:
    """``subprocess.Popen``-shaped handle for a forkserver child.

    The worker is the FORKSERVER's child, not ours, so ``waitpid`` is not
    available here. Liveness and signalling go through a pidfd
    (``pidfd_open`` works for non-children; the fd pins the process
    identity, so PID reuse can neither fake liveness nor misdirect a
    kill — a recycled PID would otherwise leak the dead worker's lease
    forever). Fallback when pidfds are unavailable: /proc scraping (the
    forkserver reaps children via SIGCHLD, so a dead worker's /proc entry
    disappears; zombie state means the forkserver itself died first).
    Exit codes are unknown either way — any "gone" is reported as 1,
    which every caller treats the same as a crash."""

    def __init__(self, pid: int):
        self.pid = pid
        self.returncode: Optional[int] = None
        self._pidfd: Optional[int] = None
        # poll()/_signal()/__del__ race from reaper, lease, and
        # memory-monitor threads; the lock keeps the close-and-None
        # transition atomic so no thread touches a stale fd number.
        self._fd_lock = threading.Lock()
        try:
            self._pidfd = os.pidfd_open(pid)
        except (AttributeError, OSError):
            # Already exited (ESRCH) or pre-5.3 kernel: poll() decides via
            # /proc below.
            pass

    def poll(self) -> Optional[int]:
        with self._fd_lock:
            if self.returncode is not None:
                return self.returncode
            if self._pidfd is not None:
                # A pidfd becomes readable exactly when the process exits.
                # select.poll, not select.select: pidfds allocated past
                # FD_SETSIZE (1024 — easily reached by a worker surge in a
                # multi-node driver) would blow up select().
                p = select.poll()
                p.register(self._pidfd, select.POLLIN)
                if p.poll(0):
                    self.returncode = 1
                    os.close(self._pidfd)
                    self._pidfd = None
                return self.returncode
            # /proc fallback stays under the SAME lock: the returncode
            # transition must be atomic with _signal()'s dead-check, or a
            # worker that died (and had its PID recycled) between that
            # check and os.kill could deliver a stray signal to an
            # unrelated process.
            try:
                with open(f"/proc/{self.pid}/stat", "rb") as f:
                    stat = f.read()
                # Field 3, after the parenthesised comm (may hold spaces).
                state = stat.rsplit(b")", 1)[1].split()[0]
            except (OSError, IndexError):
                self.returncode = 1
                return self.returncode
            if state == b"Z":
                self.returncode = 1
                return self.returncode
            return None

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired("forked-worker", timeout)
            time.sleep(0.02)
        return self.returncode

    def _signal(self, sig: int) -> None:
        try:
            with self._fd_lock:
                if self._pidfd is not None:
                    signal.pidfd_send_signal(self._pidfd, sig)
                elif self.returncode is None:
                    os.kill(self.pid, sig)
                # else: already observed dead — a raw os.kill here could
                # hit an unrelated process that recycled the PID.
        except OSError:
            pass

    def terminate(self) -> None:
        self._signal(signal.SIGTERM)

    def kill(self) -> None:
        self._signal(signal.SIGKILL)

    def __del__(self):
        with self._fd_lock:
            if self._pidfd is not None:
                try:
                    os.close(self._pidfd)
                except OSError:
                    pass
                self._pidfd = None


class _LeaseWaiter:
    """One queued lease request. Granting reserves resources on behalf of the
    waiter before waking it, so grants are FIFO per resource pool and no
    waiter can be starved by lock-acquisition races (raylets queue tasks the
    same way: leases dispatch in order per scheduling class)."""

    __slots__ = ("resources", "bundle", "event", "granted")

    def __init__(self, resources: Dict[str, float], bundle):
        self.resources = resources
        self.bundle = bundle
        self.event = threading.Event()
        self.granted = False


class WorkerHandle:
    def __init__(self, worker_id: WorkerID, proc: subprocess.Popen):
        self.worker_id = worker_id
        self.proc = proc
        self.addr: Optional[Addr] = None
        self.registered = threading.Event()
        self.idle = False
        self.dedicated = False  # actor workers are never pooled
        self.tpu = False        # forked with accelerator env (see _fork_worker)
        self.env_hash = ""      # runtime-env identity for pool matching
        self.env_dirs: List[str] = []  # cache dirs pinned against env GC
        self.tasks_received = 0        # worker-reported (worker_ping)
        self.reported_active = -1      # worker-reported in-flight tasks
        self.actor_started = False     # worker-reported actor runtime up
        self.last_ping_ts = 0.0        # when that report arrived
        self.last_progress_ts = 0.0    # when tasks_received last advanced
        self.lease_ts = 0.0            # when the current lease was granted
        # Lease generation: bumped on every grant AND reclamation, echoed
        # in return_worker so a duplicated or stale return (lost reply
        # retry, post-reclaim stragglers) can never credit someone else's
        # lease or double-pool the worker.
        self.lease_seq = 0
        self.last_used = time.monotonic()
        # Resources held by the current lease; credited back exactly once
        # (on lease return, worker kill, or death-reap — whichever first).
        self.lease_resources: Optional[Dict[str, float]] = None
        self.lease_bundle = None
        # Lease-time task metadata ({"retriable": bool, "owner": str}) used
        # by the memory monitor's worker-killing policies.
        self.task_meta: Optional[Dict[str, Any]] = None


class Node:
    def __init__(
        self,
        controller_addr: Addr,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        host: str = "127.0.0.1",
        env: Optional[Dict[str, str]] = None,
    ):
        self.node_id = NodeID.from_random()
        self.controller_addr = tuple(controller_addr)
        if resources is None:
            resources = {"CPU": float(os.cpu_count() or 1)}
        resources.setdefault("CPU", float(os.cpu_count() or 1))
        # Pod-slice topology: a node on a TPU slice (or opted into a
        # virtual slice via RAY_TPU_VIRTUAL_SLICE on the dev box)
        # advertises its slice shape at registration and exposes the
        # chip count as scalar `chips` / `slice:<id>` resources — the
        # controller's TopologyView schedules ICI-contiguous sub-slices
        # against the same totals.
        from ray_tpu.core import topology as topo

        # Virtual slices key on the NODE id: in the multi-node-in-one-
        # machine fixture every node shares the host string, and two
        # nodes must advertise two distinct 8-chip slices, not co-own
        # one grid. Real slices key on pod metadata instead.
        self.slice_info = topo.detect_slice(resources,
                                            self.node_id.hex()[:12])
        if self.slice_info is not None:
            per_host = self.slice_info.chips / self.slice_info.hosts
            resources.setdefault(resmath.CHIPS, per_host)
            resources.setdefault(
                resmath.slice_key(self.slice_info.slice_id), per_host)
        self.total_resources = dict(resources)
        self.labels = dict(labels or {})
        self._extra_env = dict(env or {})

        # Per-node shared-memory object store (plasma equivalent). The path
        # is derived from the node id so every process on the node can open
        # it without plumbing (reference: plasma socket under the session
        # dir). One store file per node keeps the multi-node-in-one-machine
        # fixture honest: cross-node reads go through read_shm_object RPC.
        self.store_path = shm_store_path(self.node_id)
        from ray_tpu._native.objstore import ShmStore

        self._shm = ShmStore.create(self.store_path,
                                    config.object_store_memory_bytes)

        self._lock = threading.Lock()
        self._available = dict(resources)
        self._bundles: Dict[BundleKey, Dict[str, Dict[str, float]]] = {}
        self._workers: Dict[WorkerID, WorkerHandle] = {}
        self._idle: List[WorkerHandle] = []
        self._waiters: List[_LeaseWaiter] = []  # FIFO lease queue
        self._queue_len = 0
        self._general_queue_len = 0  # waiters on the general (non-PG) pool
        self._death_causes: Dict[bytes, str] = {}
        self._stopped = threading.Event()
        # Worker forkserver (lazy): one pre-imported template process that
        # os.fork()s default-env CPU workers in ~10 ms (worker_pool.h:357
        # PrestartWorkers-era economics on a 1-core box).
        self._fs_lock = threading.Lock()
        self._fs_proc: Optional[subprocess.Popen] = None

        self._server = RpcServer(
            handlers={
                "lease_worker": self.lease_worker,
                "return_worker": self.return_worker,
                "register_worker": self.register_worker,
                "create_actor_worker": self.create_actor_worker,
                "kill_worker": self.kill_worker,
                "reserve_bundle": self.reserve_bundle,
                "release_bundle": self.release_bundle,
                # whole-object read fallback for peers without chunked
                # pull; kept for external/debug tooling
                # graftlint: disable=rpc-dead-endpoint
                "read_shm_object": self.read_shm_object,
                "read_shm_chunk": self.read_shm_chunk,
                "free_shm_object": self.free_shm_object,
                "worker_death_cause": self.worker_death_cause,
                "list_workers": self.list_workers,
                # reference-parity PrestartWorkers hook, reserved for
                # the autoscaler's warm-up path
                # graftlint: disable=rpc-dead-endpoint
                "prestart_workers": self.prestart_workers,
                "get_info": self.get_info,
                "ping": lambda: "pong",
                "worker_ping": self.worker_ping,
                "validate_lease": self.validate_lease,
            },
            host=host,
            name="node",
            max_workers=128,
            # All quick map/list updates; the reactor write path queues
            # their replies (non-blocking sendmsg flush), so a stalled
            # peer can no longer freeze the node's reactor for 15 s per
            # reply — inlining is bounded by handler CPU only.
            inline_methods={"return_worker", "register_worker",
                            "worker_ping", "validate_lease", "reserve_bundle",
                            "release_bundle", "kill_worker",
                            "worker_death_cause", "ping"},
        )
        self.address: Addr = self._server.addr

        # Survives controller restarts: calls retry through a fresh socket
        # (head fault tolerance — the raylet outlives the GCS).
        self._controller = ReconnectingClient(self.controller_addr)
        self._controller.call(
            "register_node", self.node_id.binary(), self.address,
            self.total_resources, self.labels,
            self.slice_info.to_dict() if self.slice_info else None)
        self._heartbeat_thread = threading.Thread(
            target=self._heartbeat_loop, name="node-heartbeat", daemon=True)
        self._heartbeat_thread.start()
        # Cluster metrics pipeline: push this process's registry to the
        # controller on the heartbeat cadence when no core-worker
        # flusher owns the push (standalone `ray_tpu start` supervisors;
        # see core/metrics_agent.py for the single-pusher arbitration).
        from ray_tpu.core.metrics_agent import MetricsAgent

        self.metrics_agent = MetricsAgent(self._controller,
                                          self.node_id.binary())
        self._reaper_thread = threading.Thread(
            target=self._reaper_loop, name="node-reaper", daemon=True)
        self._reaper_thread.start()
        self.memory_monitor = None
        if config.memory_monitor_refresh_s > 0:
            from ray_tpu.core.memory_monitor import MemoryMonitor

            self.memory_monitor = MemoryMonitor(self)
        self.log_monitor = None
        if config.log_to_driver:
            from ray_tpu.core.log_monitor import LogMonitor

            self.log_monitor = LogMonitor(self)

    # ----------------------------------------------------------- leasing

    def _pool_for(self, bundle: Optional[BundleKey]) -> Optional[Dict[str, float]]:
        if bundle is None:
            return self._available
        entry = self._bundles.get(tuple(bundle))
        return None if entry is None else entry["available"]

    def lease_worker(
        self,
        resources: Dict[str, float],
        bundle: Optional[BundleKey] = None,
        timeout: Optional[float] = None,
        dedicated: bool = False,
        runtime_env: Optional[Dict[str, Any]] = None,
        task_meta: Optional[Dict[str, Any]] = None,
        allow_spillback: bool = True,
    ) -> Dict[str, Any]:
        """Block until resources are free, then hand out a pooled or freshly
        forked worker. Returns {worker_id, addr} or {error}. ``dedicated``
        leases (actors) claim a matching warm pooled worker when available
        and fork otherwise — the worker holds the actor for life either way
        (reference: leases matched from pooled/prestarted workers,
        worker_pool.h:357; the forkserver refills the pool fast enough that
        actors can no longer starve the task pool).
        ``runtime_env`` (env_vars / working_dir) selects — or forks — a
        worker built with that environment (reference: the per-node
        runtime-env agent building envs for the worker pool,
        runtime_env_agent.py:162; pooled workers are matched by env like
        worker_pool.h's runtime_env_hash)."""
        timeout = timeout if timeout is not None else config.worker_lease_timeout_s
        bundle = tuple(bundle) if bundle is not None else None
        waiter = _LeaseWaiter(dict(resources), bundle)
        with self._lock:
            if self._pool_for(bundle) is None:
                return {"error": f"unknown bundle {bundle}"}
            depth = config.lease_spillback_queue_depth
            if (allow_spillback and not dedicated and bundle is None
                    and depth and self._general_queue_len >= depth):
                # Instant spillback: the caller re-picks with this node
                # excluded rather than queueing behind a deep backlog on
                # the GENERAL pool (bundle waiters don't contend with it)
                # (reference: hybrid policy spillback redirects).
                return {"error": f"spillback: lease queue depth "
                        f"{self._general_queue_len}"}
            self._waiters.append(waiter)
            self._queue_len += 1
            if bundle is None:
                self._general_queue_len += 1
            self._drain_waiters_locked()
        granted = waiter.event.wait(timeout)
        with self._lock:
            self._queue_len -= 1
            if bundle is None:
                self._general_queue_len -= 1
            if not waiter.granted:
                # Timed out (or lost a race): withdraw from the queue. The
                # granted flag is only ever set under this lock, so this
                # check-and-remove cannot miss a concurrent grant.
                if waiter in self._waiters:
                    self._waiters.remove(waiter)
                if not waiter.granted:
                    return {"error": "lease timeout"}
        needs_tpu = resources.get("TPU", 0) > 0
        env_hash = _runtime_env_hash(runtime_env)
        try:
            if dedicated:
                # Actors claim a warm pooled worker ONLY when the
                # forkserver can refill that kind in ~10 ms (default-env
                # CPU workers); TPU / custom-env workers cost seconds to
                # respawn, so handing those to an actor for life would
                # starve the task pool — they always fork (reference:
                # leases matched from prestarted workers, worker_pool.h:357).
                handle = None
                if (config.worker_forkserver_enabled and not needs_tpu
                        and not env_hash):
                    handle = self._take_idle_worker(needs_tpu, env_hash,
                                                    claim_dedicated=True)
                if handle is None:
                    handle = self._fork_worker(dedicated=True,
                                               needs_tpu=needs_tpu,
                                               runtime_env=runtime_env)
            else:
                handle = self._take_or_fork_worker(needs_tpu, runtime_env,
                                                   env_hash)
        except Exception as e:
            self._credit(resources, bundle)
            from ray_tpu.runtime_env import RuntimeEnvBuildError

            # Permanent = the same spec fails identically on every node
            # (bad pip requirement, missing image root): callers abort
            # instead of retrying until their lease deadline.
            return {"error": f"worker start failed: {e!r}",
                    "permanent": isinstance(e, RuntimeEnvBuildError)}
        with self._lock:
            handle.lease_resources = dict(resources)
            handle.lease_bundle = bundle
            handle.task_meta = dict(task_meta) if task_meta else None
            handle.last_used = time.monotonic()
            handle.lease_ts = time.monotonic()
            handle.lease_seq += 1
            lease_seq = handle.lease_seq
        return {"worker_id": handle.worker_id.binary(), "addr": handle.addr,
                "lease_seq": lease_seq, "lease_ts": handle.lease_ts}

    def _credit(self, resources: Dict[str, float], bundle) -> None:
        with self._lock:
            pool = self._pool_for(bundle)
            if pool is not None:
                resmath.credit(pool, resources)
            self._drain_waiters_locked()

    def _credit_lease_locked(self, handle: WorkerHandle) -> None:
        if handle.lease_resources is None:
            return
        pool = self._pool_for(handle.lease_bundle)
        if pool is not None:
            resmath.credit(pool, handle.lease_resources)
        handle.lease_resources = None
        handle.lease_bundle = None

    def _drain_waiters_locked(self) -> None:
        """Grant queued leases FIFO per resource pool. A blocked head only
        blocks later waiters on the *same* pool (general vs per-bundle), so
        placement-group leases can't wedge the general queue or vice versa."""
        blocked_pools = set()
        still_waiting: List[_LeaseWaiter] = []
        for waiter in self._waiters:
            pool_key = waiter.bundle  # None = general pool
            if pool_key in blocked_pools:
                still_waiting.append(waiter)
                continue
            pool = self._pool_for(waiter.bundle)
            if pool is not None and resmath.take(pool, waiter.resources):
                waiter.granted = True
                waiter.event.set()
            else:
                blocked_pools.add(pool_key)
                still_waiting.append(waiter)
        self._waiters = still_waiting

    def return_worker(self, worker_id_bytes: bytes,
                      resources: Dict[str, float],
                      bundle: Optional[BundleKey] = None,
                      dead: bool = False,
                      lease_seq: Optional[int] = None) -> None:
        worker_id = WorkerID(worker_id_bytes)
        bundle = tuple(bundle) if bundle is not None else None
        with self._lock:
            handle = self._workers.get(worker_id)
            if handle is not None:
                if lease_seq is not None and lease_seq != handle.lease_seq:
                    # Stale or duplicated return (retried over a lossy
                    # link, or the lease was already reclaimed/re-granted):
                    # acting on it would credit the CURRENT holder's lease
                    # or double-pool the worker.
                    return
                self._credit_lease_locked(handle)
                handle.task_meta = None
                if dead or handle.proc.poll() is not None:
                    self._remove_worker_locked(handle)
                elif not handle.dedicated and not handle.idle:
                    handle.idle = True
                    handle.last_used = time.monotonic()
                    self._idle.append(handle)
            # Unknown handle => kill_worker or the reaper already credited
            # this lease; crediting again here would double-count.
            self._drain_waiters_locked()

    def _take_idle_worker(self, needs_tpu: bool, env_hash: str,
                          claim_dedicated: bool = False
                          ) -> Optional[WorkerHandle]:
        with self._lock:
            kept: List[WorkerHandle] = []
            found = None
            while self._idle:
                handle = self._idle.pop()
                if handle.proc.poll() is not None:
                    self._remove_worker_locked(handle)
                elif (found is None and handle.tpu == needs_tpu
                        and handle.env_hash == env_hash):
                    handle.idle = False
                    # Claimed-for-actor transition happens UNDER the lock:
                    # the chaos kill hook picks pooled victims by this flag
                    # and must never see a just-claimed actor worker as fair
                    # game.
                    if claim_dedicated:
                        handle.dedicated = True
                    found = handle
                else:
                    kept.append(handle)
            self._idle.extend(kept)
            return found

    def _take_or_fork_worker(self, needs_tpu: bool = False,
                             runtime_env: Optional[Dict[str, Any]] = None,
                             env_hash: str = "") -> WorkerHandle:
        found = self._take_idle_worker(needs_tpu, env_hash)
        if found is not None:
            return found
        return self._fork_worker(needs_tpu=needs_tpu,
                                 runtime_env=runtime_env)

    def _fork_worker(self, dedicated: bool = False,
                     needs_tpu: bool = False,
                     runtime_env: Optional[Dict[str, Any]] = None
                     ) -> WorkerHandle:
        if (config.worker_forkserver_enabled and not needs_tpu
                and not runtime_env):
            try:
                return self._fork_worker_fs(dedicated)
            except _ForkserverError:
                # Template unavailable/crashed: fall back to a fresh spawn.
                # Post-fork failures (registration timeout, child death)
                # propagate — they are worker failures, not template ones,
                # and retrying them would double the caller's wait.
                pass
        worker_id = WorkerID.from_random()
        workdir = None
        python_exe = sys.executable
        env_paths: List[str] = []
        extra_vars: Optional[Dict[str, str]] = None
        env_dirs: List[str] = []
        if runtime_env:
            # Full env build (working_dir + py_modules + pip venv); any
            # failure raises and becomes the lease error (reference: the
            # raylet failing leases on runtime-env agent build errors).
            from ray_tpu.runtime_env import build_env

            built = build_env(runtime_env, self._controller)
            extra_vars = built["env_vars"]
            workdir = built["cwd"]
            env_paths = [p for p in built["pythonpath"] if p != workdir]
            env_dirs = built.get("env_dirs", [])
            if built["python"]:
                python_exe = built["python"]
        env = self._spawn_env(strip_accel=not needs_tpu,
                              extra_vars=extra_vars)
        front = ([workdir] if workdir else []) + env_paths
        if front:
            # working_dir + py_modules go FIRST so they shadow base-env
            # modules of the same name.
            env["PYTHONPATH"] = os.pathsep.join(
                front + [p for p in env.get("PYTHONPATH", "").split(
                    os.pathsep) if p])
        stdout = stderr = None
        try:
            if config.log_to_driver:
                # Unbuffered so task prints reach the log files (and thus
                # the driver) promptly rather than on process exit.
                env["PYTHONUNBUFFERED"] = "1"
                # Redirect worker output to per-worker session log files;
                # the log monitor tails them and streams lines to drivers
                # (reference: default_worker.py stdout/stderr files under
                # session_latest/logs + log_monitor.py).
                from ray_tpu.core.log_monitor import worker_log_paths

                out_path, err_path = worker_log_paths(self.node_id.hex(),
                                                      worker_id.hex())
                stdout = open(out_path, "ab", buffering=0)
                stderr = open(err_path, "ab", buffering=0)
            proc = subprocess.Popen(
                [python_exe, "-m", "ray_tpu.core.worker_main",
                 "--node-host", self.address[0],
                 "--node-port", str(self.address[1]),
                 "--controller-host", self.controller_addr[0],
                 "--controller-port", str(self.controller_addr[1]),
                 "--node-id", self.node_id.hex(),
                 "--worker-id", worker_id.hex()],
                env=env,
                cwd=workdir or None,
                stdout=stdout,
                stderr=stderr,
            )
        finally:
            # The child holds its own copies of the fds.
            for f in (stdout, stderr):
                if f is not None:
                    f.close()
        handle = WorkerHandle(worker_id, proc)
        handle.dedicated = dedicated
        handle.tpu = needs_tpu
        handle.env_hash = _runtime_env_hash(runtime_env)
        handle.env_dirs = env_dirs
        if env_dirs:
            # HOST-global GC pins (ENV_ROOT is shared across same-host
            # nodes): any node's GC honors this worker's pid.
            from ray_tpu.runtime_env import pin_env_dir

            for d in env_dirs:
                pin_env_dir(d, worker_id.hex(), proc.pid)
        with self._lock:
            self._workers[worker_id] = handle
        self._wait_registered(handle)
        return handle

    def _wait_registered(self, handle: WorkerHandle) -> None:
        """Fail FAST if the process dies before registering (chaos kill, bad
        env): waiting out the full timeout would eat the caller's whole
        lease deadline and turn one crash into a task failure."""
        proc = handle.proc
        worker_id = handle.worker_id
        deadline = time.monotonic() + config.worker_start_timeout_s
        while not handle.registered.wait(0.2):
            if proc.poll() is not None:
                with self._lock:
                    self._workers.pop(worker_id, None)
                raise RuntimeError(
                    f"worker {worker_id.hex()} died before registering "
                    f"(exit {proc.returncode})")
            if time.monotonic() > deadline:
                proc.kill()
                with self._lock:
                    self._workers.pop(worker_id, None)
                raise TimeoutError(
                    f"worker {worker_id.hex()} failed to register")

    def _spawn_env(self, strip_accel: bool,
                   extra_vars: Optional[Dict[str, str]] = None
                   ) -> Dict[str, str]:
        """Base environment for worker AND template processes: node extras,
        optional accelerator-hook strip, user runtime-env vars, then repo +
        sys.path merged onto PYTHONPATH.

        ``strip_accel``: CPU-only workers skip accelerator attach — site
        hooks keyed on these vars import jax (+PJRT registration) into
        EVERY python process, a ~2s startup tax per fork that pure-CPU
        task workers never need. TPU-resourced leases keep them.

        ``extra_vars`` (runtime_env env_vars) land BEFORE the PYTHONPATH
        merge, so a user-supplied PYTHONPATH joins the inherited tail
        instead of clobbering the pkg-root entry the worker needs to
        import ray_tpu; and AFTER the accel strip, so a runtime_env that
        sets an accelerator var deliberately keeps it."""
        env = dict(os.environ)
        env.update(self._extra_env)
        if strip_accel:
            for var in config.accel_env_vars.split(","):
                if var:
                    env.pop(var.strip(), None)
        if extra_vars:
            env.update(extra_vars)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        extra_paths = [pkg_root] + [p for p in sys.path if p]
        inherited = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                     if p]
        env["PYTHONPATH"] = os.pathsep.join(
            dict.fromkeys(extra_paths + inherited))
        return env

    # ------------------------------------------------------- forkserver

    def _fork_worker_fs(self, dedicated: bool) -> WorkerHandle:
        """Fork a default-env CPU worker from the warm template process."""
        worker_id = WorkerID.from_random()
        req: Dict[str, Any] = {"worker_id": worker_id.hex(), "env": {},
                               "stdout": None, "stderr": None}
        if config.log_to_driver:
            from ray_tpu.core.log_monitor import worker_log_paths

            out_path, err_path = worker_log_paths(self.node_id.hex(),
                                                  worker_id.hex())
            req["stdout"], req["stderr"] = out_path, err_path
            req["env"]["PYTHONUNBUFFERED"] = "1"
        # Reserve the handle BEFORE forking: the warm child can reach
        # register_worker within ms of os.fork() — before the pid reply is
        # read — and an unknown worker_id would be rejected, killing it.
        handle = WorkerHandle(worker_id, _PendingProc())
        handle.dedicated = dedicated
        with self._lock:
            self._workers[worker_id] = handle
        try:
            handle.proc = _ForkedProc(self._forkserver_request(req))
        except Exception:
            with self._lock:
                self._workers.pop(worker_id, None)
            raise
        self._wait_registered(handle)
        return handle

    def _forkserver_request(self, req: Dict[str, Any]) -> int:
        """One fork round-trip on the template's pipe. Serialized — forks
        are ~10 ms, so a single in-flight request is not the bottleneck.
        All failures surface as ``_ForkserverError`` (the caller's signal
        to fall back to a fresh interpreter spawn)."""
        with self._fs_lock:
            try:
                if self._fs_proc is None or self._fs_proc.poll() is not None:
                    # Spawning the forkserver under _fs_lock is the
                    # design: the pipe protocol allows exactly one
                    # in-flight request, and a second starter would
                    # orphan the first template process.
                    # graftlint: disable=lock-held-blocking
                    self._start_forkserver_locked()
                proc = self._fs_proc
                blob = pickle.dumps(req, protocol=5)
                proc.stdin.write(struct.pack("!I", len(blob)) + blob)
                proc.stdin.flush()
                header = self._read_fs(proc, 4)
                (n,) = struct.unpack("!I", header)
                reply = pickle.loads(self._read_fs(proc, n))
            except Exception as e:
                if self._fs_proc is not None:
                    _kill_and_reap(self._fs_proc, force=True)
                    self._fs_proc = None
                raise _ForkserverError(str(e)) from e
            if "error" in reply:
                raise _ForkserverError(reply["error"])
            return reply["pid"]

    @staticmethod
    def _read_fs(proc: subprocess.Popen, n: int) -> bytes:
        """Read exactly n reply bytes with a deadline. An untimed read
        here would wedge _fs_lock forever on a descheduled/SIGSTOPped
        template — blocking every later lease AND Node.stop()."""
        deadline = time.monotonic() + config.worker_start_timeout_s
        fd = proc.stdout.fileno()
        buf = b""
        poller = select.poll()
        poller.register(fd, select.POLLIN)
        while len(buf) < n:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError("forkserver reply timed out")
            if poller.poll(min(remaining, 1.0) * 1000):
                chunk = os.read(fd, n - len(buf))
                if not chunk:
                    raise RuntimeError("forkserver pipe closed")
                buf += chunk
        return buf

    def _start_forkserver_locked(self) -> None:
        if self._stopped.is_set():
            # A lease racing stop() must not respawn the template after
            # stop() killed it — that would leak a process per stopped node.
            raise RuntimeError("node is stopped")
        env = self._spawn_env(strip_accel=True)
        stderr: Any = subprocess.DEVNULL
        if config.log_to_driver:
            d = os.path.join(config.worker_log_dir, self.node_id.hex())
            os.makedirs(d, exist_ok=True)
            stderr = open(os.path.join(d, "forkserver.log"), "ab",
                          buffering=0)
        try:
            self._fs_proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu.core.forkserver",
                 "--node-host", self.address[0],
                 "--node-port", str(self.address[1]),
                 "--controller-host", self.controller_addr[0],
                 "--controller-port", str(self.controller_addr[1]),
                 "--node-id", self.node_id.hex()],
                env=env,
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=stderr,
            )
        finally:
            if stderr is not subprocess.DEVNULL:
                stderr.close()

    def prestart_workers(self, count: int) -> int:
        """Fork ``count`` default-env workers into the idle pool, in the
        background (reference: ``PrestartWorkers``, worker_pool.h:357 —
        fire-and-forget warm-up ahead of a burst of leases/actors)."""

        def _prestart() -> None:
            failures = 0
            for _ in range(count):
                if self._stopped.is_set():
                    return
                try:
                    handle = self._fork_worker()
                except Exception as e:
                    # One bad fork must not abort the whole warm-up, but
                    # persistent failure shouldn't hot-loop either.
                    failures += 1
                    print(f"prestart fork failed ({e}); "
                          f"{failures} consecutive", file=sys.stderr)
                    if failures >= 3:
                        return
                    continue
                failures = 0
                with self._lock:
                    handle.idle = True
                    handle.last_used = time.monotonic()
                    self._idle.append(handle)

        threading.Thread(target=_prestart, name="prestart-workers",
                         daemon=True).start()
        return count

    def worker_ping(self, worker_id_bytes: bytes,
                    tasks_received: int = -1, active_tasks: int = -1,
                    actor_started: bool = False) -> Dict[str, bool]:
        """Liveness ping that also answers "does this node still know me?".
        A worker whose handle is gone from the table (lost forkserver pid
        reply, reaper false positive, any future leak path) self-terminates
        instead of orphaning — the table is the single source of truth.

        The worker self-reports its work state so the reaper can reclaim
        leases orphaned by a LOSSY NETWORK: a lost grant reply (the caller
        never learned its worker id) or a lost lease return (the task
        finished but the credit never landed) both look the same from
        here — a lease held while the worker sits demonstrably idle."""
        with self._lock:
            handle = self._workers.get(WorkerID(worker_id_bytes))
            if handle is not None and tasks_received >= 0:
                if tasks_received != handle.tasks_received:
                    # The worker executed something since the last ping:
                    # a pipelined lease (owner pushes task after task on
                    # one grant) is ALIVE, however old its lease_ts.
                    handle.last_progress_ts = time.monotonic()
                handle.tasks_received = tasks_received
                handle.reported_active = active_tasks
                handle.actor_started = actor_started
                handle.last_ping_ts = time.monotonic()
            # Fleet-size-adaptive cadence: 2,000 workers at the default
            # 2 s interval is 1,000 pings/s on one supervisor — pings
            # starve, workers count misses, and the orphan-suicide guard
            # kills LIVE actors (the envelope-scale cascade). Capping the
            # aggregate rate at ~50/s keeps the control plane flat at any
            # fleet size.
            interval = self._suggested_ping_interval_locked()
        return {"known": handle is not None, "interval": interval}

    def _suggested_ping_interval_locked(self) -> float:
        return max(2.0, 0.02 * len(self._workers))

    def validate_lease(self, worker_id_bytes: bytes, lease_seq: int) -> bool:
        """Is ``lease_seq`` still the worker's CURRENT lease? Late task
        pushes (delayed past the reclamation window on a chaos-slow link)
        call this before executing: a reclaimed-then-re-granted worker must
        not run the stale push concurrently with the new lease's task —
        the seq token protects accounting, this check protects execution."""
        with self._lock:
            handle = self._workers.get(WorkerID(worker_id_bytes))
            return handle is not None and handle.lease_seq == lease_seq

    def register_worker(self, worker_id_bytes: bytes, addr: Addr) -> Dict[str, Any]:
        worker_id = WorkerID(worker_id_bytes)
        with self._lock:
            handle = self._workers.get(worker_id)
        if handle is None:
            return {"error": "unknown worker"}
        handle.addr = tuple(addr)
        handle.registered.set()
        return {"ok": True}

    def create_actor_worker(self, resources: Dict[str, float],
                            bundle: Optional[BundleKey] = None,
                            timeout: Optional[float] = None,
                            runtime_env: Optional[Dict[str, Any]] = None
                            ) -> Dict[str, Any]:
        """Lease a dedicated worker for an actor — warm pooled worker when
        one matches, else a ~10 ms forkserver fork."""
        return self.lease_worker(resources, bundle=bundle, timeout=timeout,
                                 dedicated=True, runtime_env=runtime_env)

    def kill_worker(self, worker_id_bytes: bytes, force: bool = True,
                    reason: Optional[str] = None) -> None:
        worker_id = WorkerID(worker_id_bytes)
        with self._lock:
            handle = self._workers.get(worker_id)
            if reason is not None:
                self._death_causes[worker_id_bytes] = reason
                while len(self._death_causes) > 256:
                    self._death_causes.pop(next(iter(self._death_causes)))
        if handle is None:
            return
        _kill_and_reap(handle.proc, force)
        with self._lock:
            self._credit_lease_locked(handle)
            self._remove_worker_locked(handle)
            self._drain_waiters_locked()

    def worker_death_cause(self, worker_id_bytes: bytes) -> Optional[str]:
        """Why a worker was killed by the node itself (e.g. the memory
        monitor) — lets a task owner turn a generic worker-crash into
        :class:`OutOfMemoryError` (reference: the raylet attaches a death
        cause to disconnect replies)."""
        with self._lock:
            return self._death_causes.get(worker_id_bytes)

    def _remove_worker_locked(self, handle: WorkerHandle) -> None:
        self._workers.pop(handle.worker_id, None)
        if handle in self._idle:
            self._idle.remove(handle)
        if handle.env_dirs:
            from ray_tpu.runtime_env import unpin_env_dir

            for d in handle.env_dirs:
                unpin_env_dir(d, handle.worker_id.hex())
        if handle.proc.poll() is not None:
            try:
                handle.proc.wait(timeout=0)
            except (subprocess.TimeoutExpired, OSError):
                pass

    # ----------------------------------------------------------- bundles

    def reserve_bundle(self, pg_id: bytes, index: int,
                       resources: Dict[str, float]) -> bool:
        with self._lock:
            if (pg_id, index) in self._bundles:
                return True  # idempotent: already reserved here
            if not resmath.take(self._available, resources):
                return False
            self._bundles[(pg_id, index)] = {
                "resources": dict(resources),
                "available": dict(resources),
            }
            return True

    def release_bundle(self, pg_id: bytes, index: int) -> None:
        with self._lock:
            entry = self._bundles.pop((pg_id, index), None)
            if entry is not None:
                resmath.credit(self._available, entry["resources"])
            self._drain_waiters_locked()

    # --------------------------------------------------------- lifecycle

    def _heartbeat_loop(self) -> None:
        """Delta-style resource sync (the reference's RaySyncer streams
        versioned deltas, ray_syncer.h:88 — polling full views doesn't
        scale): the availability payload ships only when it CHANGED since
        the last beat, with a periodic full refresh as the safety net;
        unchanged beats are liveness-only. At thousands of mostly-idle
        nodes this cuts the controller's per-beat work to a timestamp
        touch."""
        last_sent = None
        beats_since_full = 0
        seq = 0
        while not self._stopped.wait(config.heartbeat_period_s):
            try:
                if config.faultinject_path:
                    # Chaos: a delay rule here PAUSES this node's beats
                    # (the controller declares it dead past the health
                    # threshold); an error rule drops individual beats.
                    from ray_tpu.util import faultinject

                    faultinject.check("node.heartbeat")
                with self._lock:
                    available = dict(self._available)
                    queue_len = self._queue_len
                state = (available, queue_len)
                beats_since_full += 1
                if (state == last_sent and beats_since_full
                        < config.heartbeat_full_refresh_beats):
                    payload = None  # liveness-only delta
                else:
                    payload = available
                # Monotonic sync version: each beat snapshots the view at a
                # strictly later point, so the controller can drop reordered
                # (stale) beats (ray_syncer.h:88 versioned NodeState).
                seq += 1
                t_hb = time.perf_counter()
                reply = self._controller.call(
                    "heartbeat", self.node_id.binary(), payload, queue_len,
                    seq, timeout=5.0)
                if config.core_metrics_enabled:
                    from ray_tpu.core import coremetrics as cm

                    # Node-id label: the intended per-node grain — series
                    # are bounded by live membership (the controller drops
                    # a dead node's series with the node), not request
                    # volume.
                    # graftlint: disable=metrics-label-cardinality
                    cm.NODE_HEARTBEAT_RTT.observe(
                        time.perf_counter() - t_hb,
                        {"node": self.node_id.hex()[:8]})
                if payload is not None:
                    # Only a DELIVERED full beat counts as sent: a failed
                    # RPC must retry the payload next beat, or the
                    # controller schedules on stale availability for the
                    # whole refresh window.
                    last_sent = state
                    beats_since_full = 0
                if reply and not reply.get("known", True):
                    # A restarted controller doesn't know us: re-register
                    # (membership is heartbeat-driven, not persisted), and
                    # follow with a full state refresh.
                    self._controller.call(
                        "register_node", self.node_id.binary(), self.address,
                        self.total_resources, self.labels,
                        self.slice_info.to_dict() if self.slice_info
                        else None, timeout=5.0)
                    last_sent = None
            except Exception:
                # Miss enough beats and the head declares this node dead
                # — the operator needs the trail on THIS side too.
                log_every("node.heartbeat", 15.0, logger,
                          "heartbeat to controller failed", exc_info=True)

    def _reaper_loop(self) -> None:
        last_env_gc = time.monotonic()
        while not self._stopped.wait(5.0):
            now = time.monotonic()
            if (config.runtime_env_cache_bytes > 0
                    and now - last_env_gc > 60.0):
                last_env_gc = now
                self._gc_runtime_envs()
            self._reclaim_undelivered_leases(now)
            with self._lock:
                # Dead workers anywhere (incl. dedicated actor workers whose
                # process crashed): credit their lease and forget them.
                for handle in list(self._workers.values()):
                    if handle.proc.poll() is not None:
                        self._credit_lease_locked(handle)
                        self._remove_worker_locked(handle)
                # Idle-too-long pooled workers.
                keep: List[WorkerHandle] = []
                for handle in self._idle:
                    if handle.worker_id not in self._workers:
                        continue
                    if now - handle.last_used > config.idle_worker_keep_s:
                        _kill_and_reap(handle.proc, force=False)
                        self._remove_worker_locked(handle)
                    else:
                        keep.append(handle)
                self._idle = keep
                self._drain_waiters_locked()

    def _reclaim_undelivered_leases(self, now: float) -> None:
        """Reclaim leases orphaned by a lossy network. Two shapes, both
        detected through the worker's own reports (worker_ping):

        * POOLED worker leased but demonstrably IDLE (active==0 reported
          well after the grant, lease old): either the grant reply never
          reached the caller (no push will ever come — deps resolve
          before leasing, so a heard grant is pushed within an RPC) or
          the task finished and the lease RETURN was lost. Credit the
          lease and re-pool. A pathologically late push still executes
          fine (the worker accepts it; the lease GENERATION token keeps
          its eventual return from corrupting accounting).
        * DEDICATED fork whose actor runtime NEVER started (the
          create_actor_worker reply was lost; the controller retried
          elsewhere): credit and kill. Uses 3x the window — a live
          actor's start_actor is pushed right after the lease, but
          controller storms deserve slack. Actors that DID start are
          never touched (they hold their lease for life, however idle).

        Reclamation requires the idle report to POSTDATE the grant: when
        pings themselves starve (overloaded node) we cannot distinguish
        lost-grant from busy-with-stale-report — do nothing."""
        timeout_s = config.lease_undelivered_timeout_s
        if timeout_s <= 0:
            return
        victims: List[WorkerHandle] = []
        with self._lock:
            ping_fresh = max(6.0, 3 * self._suggested_ping_interval_locked())
            for handle in list(self._workers.values()):
                if (handle.lease_resources is None or not handle.lease_ts
                        or handle.reported_active != 0
                        or handle.last_ping_ts < handle.lease_ts + 2.0
                        or now - handle.last_ping_ts > ping_fresh
                        or handle.proc.poll() is not None):
                    continue
                if (not handle.dedicated
                        and now - handle.lease_ts > timeout_s
                        # A pipelined lease (owner pushes task after task
                        # on one grant) shows recent execution progress —
                        # it is alive however old the grant is.
                        and now - handle.last_progress_ts > timeout_s):
                    self._credit_lease_locked(handle)
                    handle.lease_ts = 0.0
                    handle.lease_seq += 1  # invalidate straggler returns
                    if not handle.idle:
                        handle.idle = True
                        handle.last_used = now
                        self._idle.append(handle)
                elif (handle.dedicated and not handle.actor_started
                        and now - handle.lease_ts > 3 * timeout_s):
                    self._credit_lease_locked(handle)
                    handle.lease_ts = 0.0
                    handle.lease_seq += 1
                    self._remove_worker_locked(handle)
                    victims.append(handle)
            if victims or self._waiters:
                self._drain_waiters_locked()
        for handle in victims:
            _kill_and_reap(handle.proc, force=True)

    def _gc_runtime_envs(self) -> None:
        """Evict LRU runtime-env cache dirs past the budget, pinning every
        dir a live worker was built from (reference: the runtime-env
        agent's URI refcounting + cache eviction, runtime_env/plugin.py)."""
        from ray_tpu.runtime_env import gc_envs

        with self._lock:
            in_use = {d for h in self._workers.values()
                      for d in h.env_dirs if h.proc.poll() is None}
        try:
            gc_envs(config.runtime_env_cache_bytes, in_use)
        except Exception:
            # A gc pass that always fails fills the disk with dead venvs.
            log_every("node.env_gc", 60.0, logger,
                      "runtime-env cache gc failed", exc_info=True)

    def read_shm_object(self, oid_bytes: bytes) -> Optional[bytes]:
        """Serve a whole object from this node's store (or its spill dir) to
        a remote reader — the small-object node-to-node path (reference:
        ObjectManager Push/Pull, object_manager.h:117). Large objects go
        through read_shm_chunk."""
        view = self._shm.get_view(oid_bytes)
        if view is not None:
            try:
                return bytes(view.data)
            finally:
                view.release()
        return self._read_spill(oid_bytes)

    def read_shm_chunk(self, oid_bytes: bytes, offset: int,
                       length: int) -> Optional[Tuple[int, bytes]]:
        """Chunked node-to-node transfer: returns (total_size, chunk bytes)
        for the requested range, or None when the object is gone (evicted and
        not spilled). The object is pinned only for the duration of the copy,
        so a many-chunk pull never wedges eviction (reference: 64 MiB chunked
        pulls, object_manager.h:117 / pull_manager.h:52)."""
        view = self._shm.get_view(oid_bytes)
        if view is not None:
            try:
                total = len(view.data)
                # One defensive copy (the view is released before the RPC
                # reply ships), wrapped as a PickleBuffer so the transport
                # sends it out-of-band — no further pickle copy on either
                # end (PEP 574 framing in rpc.py).
                chunk = bytes(view.data[offset:offset + length])
            finally:
                view.release()
            return total, pickle.PickleBuffer(chunk)
        path = spill_file(self.node_id, oid_bytes)
        try:
            total = os.path.getsize(path)
            with open(path, "rb") as f:
                f.seek(offset)
                return total, pickle.PickleBuffer(f.read(length))
        except OSError:
            return None

    def _read_spill(self, oid_bytes: bytes) -> Optional[bytes]:
        try:
            with open(spill_file(self.node_id, oid_bytes), "rb") as f:
                return f.read()
        except OSError:
            return None

    def free_shm_object(self, oid_bytes: bytes) -> None:
        """Owner-driven free: reclaim the object's store slot and any spill
        file (reference: FreeObjects in node_manager.proto; with automatic
        ref counting the owner calls this when the cluster-wide handle count
        hits zero)."""
        self._shm.delete(oid_bytes)
        try:
            os.unlink(spill_file(self.node_id, oid_bytes))
        except OSError:
            pass

    def kill_random_pooled_worker(self, rng) -> bool:
        """Chaos/testing hook: SIGKILL one random pooled (non-actor) worker
        process. Keeps worker-table invariants inside Node (the reaper
        credits the lease and forgets the corpse)."""
        import signal

        with self._lock:
            # pid > 0 excludes _PendingProc placeholders (pid -1):
            # os.kill(-1, SIGKILL) would massacre every signallable process.
            victims = [h for h in self._workers.values()
                       if not h.dedicated and h.proc.pid > 0
                       and h.proc.poll() is None]
        if not victims:
            return False
        victim = rng.choice(victims)
        try:
            os.kill(victim.proc.pid, signal.SIGKILL)
            return True
        except OSError:
            return False

    def list_workers(self) -> List[Dict[str, Any]]:
        """Registered worker processes (for the state CLI's stack dumps —
        the py-spy-equivalent introspection path)."""
        with self._lock:
            return [{
                "worker_id": h.worker_id.hex(),
                "addr": h.addr,
                "pid": h.proc.pid,
                "idle": h.idle,
                "dedicated": h.dedicated,
            } for h in self._workers.values() if h.addr is not None]

    def get_info(self) -> Dict[str, Any]:
        # Disk scan outside the scheduling lock: an observability RPC must
        # never stall lease/return paths behind slow IO.
        spilled = self._spilled_bytes()
        with self._lock:
            return {
                "node_id": self.node_id.hex(),
                "addr": self.address,
                "resources": dict(self.total_resources),
                "available": dict(self._available),
                "labels": dict(self.labels),
                "num_workers": len(self._workers),
                "num_idle": len(self._idle),
                "num_oom_kills": (self.memory_monitor.total_kills
                                  if self.memory_monitor else 0),
                "store_used_bytes": self._shm.used_bytes(),
                "store_capacity_bytes": self._shm.capacity(),
                "spilled_bytes": spilled,
            }

    def _spilled_bytes(self) -> int:
        total = 0
        try:
            with os.scandir(spill_dir(self.node_id)) as it:
                for entry in it:
                    try:
                        total += entry.stat().st_size
                    except OSError:
                        pass
        except OSError:
            pass
        return total

    def stop(self) -> None:
        self._stopped.set()
        self.metrics_agent.stop()
        if self.memory_monitor is not None:
            self.memory_monitor.stop()
        if self.log_monitor is not None:
            self.log_monitor.stop()
        with self._lock:
            workers = list(self._workers.values())
        for handle in workers:
            _kill_and_reap(handle.proc, force=True)
        with self._fs_lock:
            if self._fs_proc is not None:
                _kill_and_reap(self._fs_proc, force=True)
                self._fs_proc = None
        try:
            self._controller.call("unregister_node", self.node_id.binary(),
                                  timeout=2.0)
        except Exception:  # graftlint: disable=swallowed-exception
            # Best-effort goodbye at shutdown: the head reaps us by
            # heartbeat timeout regardless.
            pass
        self._controller.close()
        self._server.stop()
        try:
            self._shm.close()
            os.unlink(self.store_path)
        except OSError:
            pass
        import shutil

        shutil.rmtree(spill_dir(self.node_id), ignore_errors=True)
        shutil.rmtree(os.path.join(config.worker_log_dir,
                                   self.node_id.hex()), ignore_errors=True)
