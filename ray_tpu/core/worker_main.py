"""Worker process entrypoint.

Analogue of the reference's ``python/ray/_private/workers/default_worker.py``:
forked by the node supervisor, embeds a CoreWorker, registers its RPC address
back with the node, and then serves pushed tasks until told to shut down or
its node disappears (orphan protection — the reference's workers die with
their raylet too).
"""

from __future__ import annotations

import argparse
import sys
import time


def run(node_addr, controller_addr, node_id_hex: str,
        worker_id_hex: str) -> int:
    """Embed a CoreWorker and serve until shutdown. Shared by the spawned
    entrypoint below and by forkserver children (``core/forkserver.py``),
    which skip interpreter+import startup entirely."""
    from ray_tpu.core.ids import NodeID, WorkerID
    from ray_tpu.core.rpc import RpcClient, RpcError
    from ray_tpu.core.runtime import CoreWorker, set_core_worker
    core = CoreWorker(
        mode="worker",
        controller_addr=controller_addr,
        node_addr=node_addr,
        node_id=NodeID.from_hex(node_id_hex),
        worker_id=WorkerID.from_hex(worker_id_hex),
    )
    set_core_worker(core)

    node_client = RpcClient(node_addr)
    reply = node_client.call("register_worker", core.worker_id.binary(),
                             core.addr)
    if "error" in reply:
        print(f"worker registration failed: {reply}", file=sys.stderr)
        return 1

    # Serve until shutdown; exit if the node supervisor disappears OR has
    # forgotten us (orphan protection both ways — a worker missing from the
    # node's table can never be reaped, so it must exit itself).
    # "Disappeared" requires CONSECUTIVE misses: a single slow ping under
    # load (e.g. a 1000-actor storm starving the node's reader threads)
    # must not make healthy workers mass-suicide — that cascaded into
    # dead actors at envelope scale. known=False stays authoritative.
    # The node suggests the cadence (fleet-size adaptive, ~50 pings/s
    # aggregate); jitter spreads the fleet so intervals don't phase-lock
    # into synchronized bursts.
    import random as _random

    misses = 0
    interval = 2.0
    transient = False
    while not core._shutdown.is_set():
        time.sleep(interval * (0.75 + 0.5 * _random.random()))
        try:
            # Long intervals (big fleets) use a transient connection per
            # ping: a persistent socket per worker means a reader THREAD
            # per worker inside the node supervisor — at 5,000 actors
            # that alone exhausts the node's thread/mmap budget.
            if transient:
                client = RpcClient(node_addr)
            else:
                client = node_client
            try:
                reply = client.call(
                    "worker_ping", core.worker_id.binary(),
                    core.tasks_received, core.active_tasks,
                    core._actor_runtime is not None,
                    timeout=max(10.0, interval))
            finally:
                if transient:
                    client.close()
            if not reply.get("known", True):
                break
            interval = float(reply.get("interval", 2.0))
            go_transient = interval > 10.0
            if go_transient and not transient:
                node_client.close()  # free the node-side reader thread
            elif transient and not go_transient:
                # Fleet shrank back: re-dial the persistent connection
                # (the old one was closed when we went transient).
                node_client = RpcClient(node_addr)
            transient = go_transient
            misses = 0
        except (RpcError, TimeoutError):
            misses += 1
            if misses >= 5:
                break
    core.shutdown()
    return 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--node-host", required=True)
    parser.add_argument("--node-port", type=int, required=True)
    parser.add_argument("--controller-host", required=True)
    parser.add_argument("--controller-port", type=int, required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--worker-id", required=True)
    args = parser.parse_args()
    return run((args.node_host, args.node_port),
               (args.controller_host, args.controller_port),
               args.node_id, args.worker_id)


if __name__ == "__main__":
    sys.exit(main())
