"""Controller: the cluster control plane (GCS equivalent).

Analogue of the reference's Global Control Service
(``src/ray/gcs/gcs_server/gcs_server.h:223-289``): one logical process holding
cluster-level metadata — node membership + health (``GcsNodeManager``,
``GcsHealthCheckManager``), the actor directory and lifecycle state machine
*including scheduling and restarts* (``GcsActorManager`` +
``GcsActorScheduler``: actors are scheduled by the control plane, not by the
creating client, so restarts survive the creator), placement groups with
two-phase bundle reservation (``GcsPlacementGroupManager/Scheduler``), jobs
(``GcsJobManager``), a KV store used for the function table and named actors
(``GcsInternalKVManager``), and cluster-level node selection for tasks (the
cluster half of the reference's two-level scheduler,
``cluster_resource_scheduler.h``).

The data plane stays decentralized exactly as in the reference: object values
live with their owners; the controller never sees them.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import resources as resmath
from ray_tpu.core.config import config
from ray_tpu.core.ids import ActorID, NodeID, PlacementGroupID
from ray_tpu.core.pubsub import Pubsub
from ray_tpu.core.rpc import ClientPool, RpcServer
from ray_tpu.core.rpc_stubs import CoreWorkerStub, NodeStub
from ray_tpu.util.ratelimit import log_every

logger = logging.getLogger(__name__)

Addr = Tuple[str, int]

# Actor lifecycle states (reference: gcs.proto ActorTableData.ActorState).
DEPENDENCIES_UNREADY = "DEPENDENCIES_UNREADY"
PENDING_CREATION = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


class NodeRecord:
    def __init__(self, node_id: NodeID, addr: Addr, resources: Dict[str, float],
                 labels: Dict[str, str],
                 slice_info: Optional[Dict[str, Any]] = None):
        self.node_id = node_id
        self.addr = tuple(addr)
        self.total = dict(resources)
        self.available = dict(resources)
        self.labels = dict(labels)
        # Advertised pod-slice membership (topology.SliceInfo.to_dict()):
        # feeds the controller's TopologyView for mesh-aware placement.
        self.slice_info = dict(slice_info) if slice_info else None
        self.queue_len = 0
        self.last_heartbeat = time.monotonic()
        self.alive = True
        # Last applied heartbeat seq; -1 = none yet. Re-registration resets
        # it so a restarted sender's fresh counter is accepted.
        self.sync_seq = -1

    def summary(self) -> Dict[str, Any]:
        return {
            "node_id": self.node_id.hex(),
            "addr": self.addr,
            "resources": dict(self.total),
            "available": dict(self.available),
            "labels": dict(self.labels),
            "slice": self.slice_info,
            "alive": self.alive,
            "queue_len": self.queue_len,
        }


class ActorRecord:
    def __init__(self, actor_id: ActorID, info: Dict[str, Any],
                 spec: Dict[str, Any], opts: Dict[str, Any]):
        self.actor_id = actor_id
        self.state = PENDING_CREATION
        self.addr: Optional[Tuple] = None  # (worker_addr, worker_id, node_addr)
        self.node_id: Optional[NodeID] = None
        self.info = info      # name, class_name, resources, max_restarts, ...
        self.spec = spec      # start_actor payload (cls_key, args_blob, ...)
        self.opts = opts      # scheduling options (resources, strategy, pg)
        self.num_restarts = 0
        self.incarnation = 0
        self.death_cause: Optional[str] = None


class PlacementGroupRecord:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]],
                 strategy: str):
        self.pg_id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.state = "PENDING"  # PENDING -> CREATING -> CREATED
        # bundle index -> (node_id, node addr)
        self.placement: Dict[int, Tuple[NodeID, Addr]] = {}


def _utilization(rec: NodeRecord) -> float:
    """Max fractional utilization across resource kinds (0 = idle)."""
    utils = []
    for k, tot in rec.total.items():
        if tot > 0:
            utils.append(1.0 - rec.available.get(k, 0.0) / tot)
    return max(utils) if utils else 0.0


class Controller:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 persist_path: Optional[str] = None):
        """``persist_path`` enables control-plane fault tolerance: cluster
        metadata (KV, jobs, named actors, actor/PG records) snapshots to
        disk and a restarted controller rebuilds from it (reference: GCS
        Redis persistence, ``redis_store_client.h:33`` + ``gcs_init_data.cc``
        — the durable store here is a local file, this image has no Redis).
        Node membership is NOT persisted: nodes re-register via their
        heartbeats, exactly like raylets reconnecting to a restarted GCS."""
        self._persist_path = persist_path
        self._save_lock = threading.Lock()
        self._lock = threading.RLock()
        self._nodes: Dict[NodeID, NodeRecord] = {}
        self._actors: Dict[ActorID, ActorRecord] = {}
        self._named_actors: Dict[str, ActorID] = {}
        self._kv: Dict[str, bytes] = {}
        self._jobs: Dict[str, Dict[str, Any]] = {}
        self._pgs: Dict[PlacementGroupID, PlacementGroupRecord] = {}
        self._metrics: Dict[str, List[Dict[str, Any]]] = {}
        self._metrics_ts: Dict[str, float] = {}
        # Control-plane instrumentation: plain counters bumped on the
        # handler paths (heartbeat is INLINE on the reactor — it must
        # never touch the registry lock), published by the snapshot-time
        # collector below.
        self._m_heartbeats = 0
        self._m_node_deaths = 0
        from ray_tpu.util.metrics import CounterDeltas

        self._m_deltas = CounterDeltas()
        self._task_events: List[Dict[str, Any]] = []
        # Unmet-demand signal for the autoscaler (reference:
        # GcsAutoscalerStateManager's pending resource requests): deduped
        # by shape, expiring shortly after failures stop, cleared when a
        # placement of that shape succeeds — waiting submitters retry, so
        # live demand keeps itself fresh and satisfied demand evaporates
        # (no scale-up/down oscillation from stale history).
        # shape key -> (resources, ts, labels-or-None): unmet scheduling
        # demand, labels carried so the autoscaler can match node types.
        self._pending_demand: Dict[tuple, tuple] = {}
        # Pod-slice topology view: nodes advertise their slice at
        # registration; mesh-parallel serve replicas reserve ICI-
        # contiguous sub-slices through it (never a fragment straddling
        # two slices). Internally locked — accessed outside self._lock.
        from ray_tpu.core.topology import TopologyView

        self._topology = TopologyView()
        self._clients = ClientPool()
        self._stopped = threading.Event()
        # Long-poll notification hub (reference: src/ray/pubsub/publisher.h
        # + serve's LongPollHost): actor/job/PG state transitions and KV
        # writes publish here so clients wait on pushes, not poll loops.
        self.pubsub = Pubsub()
        # Multi-host gang registry (core/multihost.py): group epochs,
        # rendezvous barriers (program-hash checks park handler threads
        # here exactly like the pubsub long-polls), fenced group KV and
        # membership beats. Internally locked — accessed off self._lock.
        from ray_tpu.core.multihost import GroupRegistry

        self.multihost = GroupRegistry()
        # Pipeline-parallel training registry (core/pipereg.py): epoch-
        # fenced per-pipeline progress records (the resume point a
        # re-formed stage gang asks for). Internally locked — accessed
        # off self._lock.
        from ray_tpu.core.pipereg import PipelineRegistry

        self.pipelines = PipelineRegistry()
        self._server = RpcServer(
            handlers={
                "register_node": self.register_node,
                "unregister_node": self.unregister_node,
                "heartbeat": self.heartbeat,
                "list_nodes": self.list_nodes,
                "pick_node": self.pick_node,
                "register_actor": self.register_actor,
                "get_actor": self.get_actor,
                "list_actors": self.list_actors,
                "get_named_actor": self.get_named_actor,
                "report_actor_failure": self.report_actor_failure,
                "kill_actor": self.kill_actor,
                "kv_put": self.kv_put,
                "kv_get": self.kv_get,
                "kv_put_fenced": self.kv_put_fenced,
                "epoch_bump": self.epoch_bump,
                # kv_del gained an in-package caller in PR 12
                # (serve.shutdown drops the serve-controller
                # checkpoint); kv_keys remains external-tooling-only.
                "kv_del": self.kv_del,
                # graftlint: disable=rpc-dead-endpoint
                "kv_keys": self.kv_keys,
                "register_job": self.register_job,
                "finish_job": self.finish_job,
                "list_jobs": self.list_jobs,
                "create_placement_group": self.create_placement_group,
                "get_placement_group": self.get_placement_group,
                "remove_placement_group": self.remove_placement_group,
                "cluster_resources": self.cluster_resources,
                "reserve_subslice": self.reserve_subslice,
                "release_subslice": self.release_subslice,
                "topology_state": self.topology_state,
                "taint_host": self.taint_host,
                "untaint_host": self.untaint_host,
                "taint_state": self.taint_state,
                "mh_register_group": self.multihost.register_group,
                "mh_drop_group": self.multihost.drop_group,
                "mh_barrier": self.multihost.barrier,
                "mh_member_beat": self.multihost.member_beat,
                "mh_group_put": self.multihost.group_put,
                "mh_group_get": self.multihost.group_get,
                "mh_group_state": self.multihost.group_state,
                "pipe_register": self.pipelines.register,
                "pipe_drop": self.pipelines.drop,
                "pipe_step_complete": self.pipelines.step_complete,
                "pipe_state": self.pipelines.state,
                "fr_dump": self.fr_dump,
                "autoscaler_state": self.autoscaler_state,
                "push_metrics": self.push_metrics,
                "list_metrics": self.list_metrics,
                "metrics_text": self.metrics_text,
                "push_task_events": self.push_task_events,
                "list_task_events": self.list_task_events,
                "psub_poll": self.pubsub.poll,
                "psub_poll_many": self.pubsub.poll_many,
                "psub_publish": self.pubsub.publish,
                # Publishers that own a key drop it at teardown so the
                # hub never pins their payload (the RL weight fan-out
                # publishes object-plane refs: a leaked key is a leaked
                # ObjectRef handle in the controller process).
                "psub_drop": self.pubsub.drop,
                "psub_snapshot": self.pubsub.snapshot,
                "psub_keys": self.pubsub.keys,
                "ping": lambda: "pong",
            },
            host=host,
            port=port,
            name="controller",
            max_workers=256,  # long-polls park handler threads
            # The reactor write path queues replies (non-blocking sendmsg
            # flush), so inline handlers can answer slow peers without
            # stalling other connections — heartbeats and pings must make
            # progress even when the pool is saturated with long-polls.
            inline_methods={"heartbeat", "ping"},
        )
        if persist_path:
            self._restore_state()
            self._persist_thread = threading.Thread(
                target=self._persist_loop, name="controller-persist",
                daemon=True)
            self._persist_thread.start()
        self._health_thread = threading.Thread(
            target=self._health_loop, name="controller-health", daemon=True)
        self._health_thread.start()
        from ray_tpu.util import metrics as um

        um.add_collector(self._collect_metrics)
        # Optional controller-side Prometheus endpoint: the whole
        # cluster's aggregated metrics as exposition text, scrapeable
        # without the dashboard (config.controller_metrics_http_port).
        self.metrics_http_addr: Optional[Addr] = None
        self._metrics_http = None
        if config.controller_metrics_http_port >= 0:
            self._start_metrics_http(host,
                                     config.controller_metrics_http_port)
        # Discovery file for the state CLI (`python -m ray_tpu status`).
        from ray_tpu.scripts import write_discovery

        write_discovery(self.address)

    def _collect_metrics(self) -> None:
        from ray_tpu.core import coremetrics as cm

        if not config.core_metrics_enabled:
            return
        with self._lock:
            pending = len(self._pending_demand)
        cm.CTRL_PENDING_DEMAND.set(float(pending))
        self._m_deltas.inc_to(cm.CTRL_HEARTBEATS, "hb", self._m_heartbeats)
        self._m_deltas.inc_to(cm.CTRL_NODE_DEATHS, "deaths",
                              self._m_node_deaths)

    def _start_metrics_http(self, host: str, port: int) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        controller = self

        class _MetricsHandler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API)
                if self.path.split("?")[0] != "/metrics":
                    self.send_response(404)
                    self.end_headers()
                    return
                try:
                    payload = controller.metrics_text().encode()
                except Exception as e:  # noqa: BLE001
                    payload = f"# metrics unavailable: {e!r}\n".encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *args):  # silence
                pass

        try:
            self._metrics_http = ThreadingHTTPServer((host, port),
                                                     _MetricsHandler)
        except OSError as e:
            logger.warning("controller /metrics endpoint failed to bind "
                           "%s:%s: %s", host, port, e)
            return
        self.metrics_http_addr = self._metrics_http.server_address
        threading.Thread(target=self._metrics_http.serve_forever,
                         name="controller-metrics-http",
                         daemon=True).start()

    # ------------------------------------------------------- persistence

    def _snapshot_state(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "kv": dict(self._kv),
                "jobs": {j: dict(info) for j, info in self._jobs.items()},
                "named_actors": {n: a.binary()
                                 for n, a in self._named_actors.items()},
                "actors": [
                    {"actor_id": rec.actor_id.binary(), "state": rec.state,
                     "addr": rec.addr,
                     "node_id": (rec.node_id.binary()
                                 if rec.node_id else None),
                     "info": dict(rec.info), "spec": dict(rec.spec),
                     "opts": dict(rec.opts),
                     "num_restarts": rec.num_restarts,
                     "incarnation": rec.incarnation,
                     "death_cause": rec.death_cause}
                    for rec in self._actors.values()],
                "pgs": [
                    {"pg_id": rec.pg_id.binary(), "bundles": rec.bundles,
                     "strategy": rec.strategy, "state": rec.state}
                    for rec in self._pgs.values()],
            }

    def save_state(self) -> None:
        if not self._persist_path:
            return
        import os
        import pickle

        # _snapshot_state copies every mutable container under the lock
        # (jobs/info/spec/opts are dict()-copied; remaining values are
        # immutable), so pickling outside the lock sees a consistent view.
        # _save_lock serializes writers (stop() racing the persist loop on
        # the shared .tmp path would corrupt the snapshot).
        with self._save_lock:
            blob = pickle.dumps(self._snapshot_state())
            if "://" in self._persist_path:
                # External store (reference: GCS-on-Redis FT,
                # redis_store_client.h:33 — here any pyarrow filesystem:
                # s3://, gs://, mock://; survives head-HOST loss, not just
                # head-process loss). Same atomic discipline as the local
                # path: write a temp object, then move — a crash mid-write
                # must never truncate the only snapshot.
                fs, path = self._external_fs()
                tmp = f"{path}.tmp-{os.getpid()}"
                with fs.open_output_stream(tmp) as f:
                    f.write(blob)
                fs.move(tmp, path)
                return
            tmp = self._persist_path + ".tmp"
            os.makedirs(os.path.dirname(self._persist_path) or ".",
                        exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, self._persist_path)

    def _external_fs(self):
        from pyarrow import fs as pafs

        return pafs.FileSystem.from_uri(self._persist_path)

    def _restore_state(self) -> None:
        import os
        import pickle

        if "://" in self._persist_path:
            import sys

            from pyarrow.lib import ArrowIOError

            try:
                fs, path = self._external_fs()
                with fs.open_input_stream(path) as f:
                    state = pickle.loads(f.read())
            except (ArrowIOError, OSError):
                return  # no snapshot yet
            except (pickle.UnpicklingError, EOFError, ValueError) as e:
                # A corrupt snapshot must not brick the replacement head:
                # starting empty (nodes re-register) beats not starting.
                print(f"controller: ignoring corrupt snapshot "
                      f"{self._persist_path}: {e!r}", file=sys.stderr)
                return
            self._apply_restored(state)
            return
        if not os.path.exists(self._persist_path):
            return
        with open(self._persist_path, "rb") as f:
            state = pickle.load(f)
        self._apply_restored(state)

    def _apply_restored(self, state: Dict[str, Any]) -> None:
        with self._lock:
            self._kv = dict(state.get("kv", {}))
            self._jobs = dict(state.get("jobs", {}))
            self._named_actors = {
                n: ActorID(b)
                for n, b in state.get("named_actors", {}).items()}
            reschedule = []
            for a in state.get("actors", []):
                rec = ActorRecord(ActorID(a["actor_id"]), a["info"],
                                  a["spec"], a["opts"])
                rec.state = a["state"]
                rec.addr = a["addr"]
                if a.get("node_id"):
                    rec.node_id = NodeID(a["node_id"])
                rec.num_restarts = a["num_restarts"]
                rec.incarnation = a["incarnation"]
                rec.death_cause = a["death_cause"]
                self._actors[rec.actor_id] = rec
                # In-flight creations/restarts lost their scheduler thread
                # with the old process: respawn it. ALIVE records keep
                # their address; if the worker died meanwhile, the first
                # caller's failure report drives the normal restart path.
                if rec.state in (PENDING_CREATION, RESTARTING):
                    reschedule.append(rec.actor_id)
            for p in state.get("pgs", []):
                pg_rec = PlacementGroupRecord(
                    PlacementGroupID(p["pg_id"]), p["bundles"],
                    p["strategy"])
                # Bundle placements referenced dead nodes; PGs return to
                # PENDING and re-reserve on the next create call (idempotent
                # 2PC), as the reference re-schedules PGs after GCS restart.
                pg_rec.state = "PENDING"
                self._pgs[pg_rec.pg_id] = pg_rec
        for actor_id in reschedule:
            threading.Thread(target=self._schedule_actor, args=(actor_id,),
                             name="actor-schedule", daemon=True).start()

    def _persist_loop(self) -> None:
        import sys

        warned = False
        while not self._stopped.wait(2.0):
            try:
                self.save_state()
                warned = False
            except Exception as e:  # noqa: BLE001
                if not warned:  # fault tolerance degrading is not silent
                    print(f"controller: state persistence failing: {e!r}",
                          file=sys.stderr)
                    warned = True

    @property
    def address(self) -> Addr:
        return self._server.addr

    # ------------------------------------------------------------- nodes

    def register_node(self, node_id_bytes: bytes, addr: Addr,
                      resources: Dict[str, float],
                      labels: Dict[str, str],
                      slice_info: Optional[Dict[str, Any]] = None) -> None:
        node_id = NodeID(node_id_bytes)
        with self._lock:
            self._nodes[node_id] = NodeRecord(node_id, addr, resources,
                                              labels, slice_info)
        if slice_info:
            from ray_tpu.core.topology import SliceInfo

            self._topology.register(node_id.hex(),
                                    SliceInfo.from_dict(slice_info))

    def unregister_node(self, node_id_bytes: bytes) -> None:
        node_id = NodeID(node_id_bytes)
        with self._lock:
            rec = self._nodes.get(node_id)
            if rec:
                rec.alive = False
        self._on_node_dead(node_id)

    def heartbeat(self, node_id_bytes: bytes,
                  available: Optional[Dict[str, float]],
                  queue_len: int, seq: Optional[int] = None) -> Dict[str, bool]:
        """Returns ``known=False`` when this controller has no record of the
        node — the signal for a live raylet to re-register after a head
        restart (node membership is not persisted; reference: raylets
        re-registering with a restarted GCS, conftest.py:532).

        ``available=None`` is a liveness-only delta beat (the node's view
        is unchanged); the record keeps its last payload (reference:
        RaySyncer's versioned delta stream vs full snapshots).

        ``seq`` is the node's monotonic sync version (reference: versioned
        NodeState snapshots, ray_syncer.h:88). A beat whose seq is not
        newer than the last applied one is dropped — a delayed full beat
        racing a newer delta can no longer regress availability until the
        periodic refresh. Beats still count for liveness either way;
        ``seq=None`` (unversioned caller) always applies."""
        with self._lock:
            self._m_heartbeats += 1  # registry-free: runs on the reactor
            rec = self._nodes.get(NodeID(node_id_bytes))
            if rec is None:
                return {"known": False}
            rec.last_heartbeat = time.monotonic()
            rec.alive = True
            if seq is not None and seq <= rec.sync_seq:
                return {"known": True, "applied": False}
            if seq is not None:
                rec.sync_seq = seq
            if available is not None:
                rec.available = dict(available)
            rec.queue_len = queue_len
            return {"known": True, "applied": True}

    def list_nodes(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [r.summary() for r in self._nodes.values()]

    def cluster_resources(self) -> Dict[str, float]:
        with self._lock:
            total: Dict[str, float] = {}
            for rec in self._nodes.values():
                if rec.alive:
                    resmath.credit(total, rec.total)
            return total

    # ------------------------------------------------- pod-slice topology

    def reserve_subslice(self, owner: str, chips: int,
                         shape: Optional[List[int]] = None
                         ) -> Optional[Dict[str, Any]]:
        """Reserve an ICI-contiguous sub-slice for ``owner`` (a replica
        id). ``shape`` pins the chip-grid rectangle (a replica's mesh
        shape); bare ``chips`` folds to the most-square block. Returns
        the assignment (slice, origin, shape, hosting nodes) or None
        when no SINGLE slice can host it contiguously — a request that
        would straddle two slices is refused, never fragmented.

        This reserves the GRID only (which chips, contiguously). The
        scalar accounting rides the normal lease path: the actor that
        spans the sub-slice requests ``chips`` / ``slice:<id>``
        resources (resources.chip_resources), which the hosting node's
        own availability tracks — controller-side scalar deduction here
        would just be overwritten by the node's next heartbeat."""
        sub = self._topology.reserve(
            owner, chips=chips,
            shape=tuple(shape) if shape else None)
        if sub is None:
            # Unmet topology demand feeds the same autoscaler signal as
            # unplaceable tasks: a provider that can add slices sees it.
            shape_key = ((resmath.CHIPS, float(chips)),)
            with self._lock:
                self._pending_demand[shape_key] = (
                    {resmath.CHIPS: float(chips)}, time.monotonic(), None)
            return None
        return sub

    def release_subslice(self, reservation_id: str) -> bool:
        """Release a sub-slice reservation (idempotent)."""
        return self._topology.release(reservation_id)

    def topology_state(self) -> Dict[str, Any]:
        """Operator view: every advertised slice's grid, free chips,
        fragmentation, and live reservations."""
        return self._topology.state()

    def taint_host(self, node_hex: str,
                   ttl_s: Optional[float] = None) -> Dict[str, Any]:
        """Demote a host from new gang/replica placement (autopilot's
        taint-host action, or an operator). Placement preference, not
        exclusion: reservations still succeed when only tainted
        capacity remains. The taint lapses after ``ttl_s`` (default
        ``config.autopilot_taint_ttl_s``) — but the health loop keeps
        re-arming it while the host fails the re-admission probe."""
        ttl = (float(ttl_s) if ttl_s is not None
               else config.autopilot_taint_ttl_s)
        self._topology.taint(node_hex, ttl)
        return {"node": node_hex, "ttl_s": ttl}

    def untaint_host(self, node_hex: str, probe: bool = True
                     ) -> Dict[str, Any]:
        """Lift a host taint early. With ``probe`` (default) the host is
        re-admitted only if its heartbeats look healthy; a host that
        fails the probe keeps its taint for another TTL."""
        if probe and not self._node_probe_ok(node_hex):
            self._topology.taint(node_hex, config.autopilot_taint_ttl_s)
            return {"node": node_hex, "untainted": False,
                    "reason": "probe-failed"}
        return {"node": node_hex,
                "untainted": self._topology.untaint(node_hex)}

    def taint_state(self) -> Dict[str, float]:
        """Live host taints: node hex -> remaining seconds."""
        return self._topology.tainted()

    def _node_probe_ok(self, node_hex: str) -> bool:
        """Re-admission probe: alive with a heartbeat fresher than the
        health-check threshold. An unknown node fails (it can't be
        placed on anyway, and re-admitting a ghost proves nothing)."""
        threshold = (config.health_check_failure_threshold
                     * config.heartbeat_period_s)
        now = time.monotonic()
        with self._lock:
            for rec in self._nodes.values():
                if rec.node_id.hex() == node_hex:
                    return (rec.alive
                            and now - rec.last_heartbeat <= threshold)
        return False

    def _health_loop(self) -> None:
        period = config.heartbeat_period_s
        threshold = config.health_check_failure_threshold * period
        while not self._stopped.wait(period):
            now = time.monotonic()
            dead_nodes = []
            with self._lock:
                for rec in self._nodes.values():
                    if rec.alive and now - rec.last_heartbeat > threshold:
                        rec.alive = False
                        dead_nodes.append(rec.node_id)
            for node_id in dead_nodes:
                self._on_node_dead(node_id)
            self._reap_dead_actors()
            # Probe-gated taint expiry: a taint about to lapse on a host
            # that still fails the re-admission probe re-arms for
            # another TTL — TTLs re-admit recovered hosts, not sick ones.
            for node_hex, left in self._topology.tainted().items():
                if left <= period and not self._node_probe_ok(node_hex):
                    self._topology.taint(
                        node_hex, config.autopilot_taint_ttl_s)

    def _reap_dead_actors(self) -> None:
        """Bound the DEAD-actor cache (records + pubsub entries) so a
        long-lived cluster churning actors doesn't grow without limit
        (reference: maximum_gcs_destroyed_actor_cached_count)."""
        cap = config.dead_actor_cache_count
        with self._lock:
            dead = [a for a, r in self._actors.items() if r.state == DEAD]
            if len(dead) <= cap:
                return
            victims = dead[:len(dead) - cap]  # dict order = oldest first
            for actor_id in victims:
                rec = self._actors.pop(actor_id)
                name = rec.info.get("name")
                if name and self._named_actors.get(name) == actor_id:
                    del self._named_actors[name]
        for actor_id in victims:
            self.pubsub.drop("actors", actor_id.hex())

    def _on_node_dead(self, node_id: NodeID) -> None:
        """Fail (and maybe restart) actors on a dead node (reference:
        GcsActorManager node-death handling, gcs_actor_manager.h:88)."""
        # Topology: a slice whose last live host died drops from the
        # view with its sub-slice reservations (the replicas holding
        # them died with the hosts; serve's reconcile re-reserves).
        self._topology.node_dead(node_id.hex())
        # Metric series from the dead node's processes stop meaning
        # anything (their counters died with them): drop them so the
        # cluster view reflects live producers only. A restarted node
        # registers a fresh id and pushes fresh cumulative snapshots —
        # never a double count.
        prefix = node_id.hex()[:8] + "/"
        with self._lock:
            self._m_node_deaths += 1
            for key in [k for k in self._metrics if k.startswith(prefix)]:
                del self._metrics[key]
                self._metrics_ts.pop(key, None)
            affected = [rec.actor_id for rec in self._actors.values()
                        if rec.node_id == node_id and rec.state == ALIVE]
        for actor_id in affected:
            self.report_actor_failure(actor_id.binary(),
                                      f"node {node_id.hex()} died")

    # ------------------------------------------------- cluster scheduling

    def pick_node(
        self,
        resources: Dict[str, float],
        strategy: Optional[Dict[str, Any]] = None,
        caller_node_id: Optional[bytes] = None,
        excluded: Optional[List[bytes]] = None,
    ) -> Optional[Dict[str, Any]]:
        """Cluster-level node selection.

        Default is the reference's hybrid policy
        (``src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.cc``):
        prefer the caller's node while its utilization is below
        ``scheduler_spread_threshold``, otherwise pick the feasible node with
        the lowest utilization (ties broken deterministically). ``spread``
        picks the least-utilized feasible node regardless of locality;
        ``node_affinity`` pins (hard) or prefers (soft) a node. Returns
        {node_id, addr} or None if infeasible.
        """
        strategy = strategy or {}
        excluded_ids = {NodeID(b) for b in (excluded or [])}
        demand_labels = (strategy.get("labels")
                         if strategy.get("kind") == "node_label" else None)
        with self._lock:
            alive = [r for r in self._nodes.values()
                     if r.alive and r.node_id not in excluded_ids]
            feasible = [r for r in alive if resmath.fits(r.total, resources)]
            shape_key = (tuple(sorted(resources.items())),
                         tuple(sorted((demand_labels or {}).items())))
            if not feasible:
                self._pending_demand[shape_key] = (
                    dict(resources), time.monotonic(), demand_labels)
                return None
            self._pending_demand.pop(shape_key, None)

            def rank(r):
                return (_utilization(r), r.queue_len, r.node_id.binary())

            def prefer_room(pool):
                with_room = [r for r in pool
                             if resmath.fits(r.available, resources)]
                return with_room or pool

            kind = strategy.get("kind", "hybrid")
            if kind == "node_affinity":
                target = NodeID.from_hex(strategy["node_id"])
                for r in feasible:
                    if r.node_id == target:
                        return self._grant(r, resources)
                if not strategy.get("soft", False):
                    return None
            elif kind == "spread":
                return self._grant(min(feasible, key=rank), resources)
            elif kind == "node_label":
                # Label policy (reference:
                # node_label_scheduling_policy.cc): hard constraints must
                # all match; soft labels prefer matching nodes but fall
                # back to any hard-matching node. Nodes with room now beat
                # lower-utilization nodes that are currently full.
                hard = strategy.get("labels") or {}
                soft = strategy.get("soft_labels") or {}
                matching = [r for r in feasible
                            if all(r.labels.get(k) == v
                                   for k, v in hard.items())]
                if not matching:
                    # Label-blocked: keep the demand visible to operators
                    # and the autoscaler, WITH its labels, so the bin-pack
                    # only counts it against label-satisfying node types.
                    self._pending_demand[shape_key] = (
                        dict(resources), time.monotonic(), demand_labels)
                    return None
                preferred = [r for r in matching
                             if all(r.labels.get(k) == v
                                    for k, v in soft.items())]
                # Having room outranks soft-label preference: a full
                # soft-match must not beat an idle hard-match.
                with_room = [r for r in matching
                             if resmath.fits(r.available, resources)]
                pool = ([r for r in preferred if r in with_room]
                        or with_room or preferred or matching)
                return self._grant(min(pool, key=rank), resources)
            elif kind == "random":
                # Random policy (reference: random_scheduling_policy.cc):
                # uniform over feasible nodes with room (load-oblivious
                # scatter for e.g. monitoring tasks).
                import random as _random

                return self._grant(_random.choice(prefer_room(feasible)),
                                   resources)

            # Hybrid: local-first below the spread threshold.
            if caller_node_id is not None:
                local = NodeID(caller_node_id)
                for r in feasible:
                    if (r.node_id == local
                            and _utilization(r) < config.scheduler_spread_threshold
                            and resmath.fits(r.available, resources)):
                        return self._grant(r, resources)
            pool = prefer_room(feasible)
            pool = sorted(pool, key=rank)
            return self._grant(pool[0], resources)

    def _grant(self, rec: NodeRecord, resources: Dict[str, float]):
        # Optimistic decrement until the next heartbeat refreshes truth.
        resmath.deduct(rec.available, resources)
        return {"node_id": rec.node_id.binary(), "addr": rec.addr}

    # ------------------------------------------------------------ actors
    #
    # The controller owns the whole actor lifecycle: REGISTER ->
    # PENDING_CREATION -> (scheduled on a node, __init__ pushed) -> ALIVE;
    # on failure, RESTARTING (num_restarts < max_restarts) -> re-scheduled,
    # else DEAD. Mirrors GcsActorManager + GcsActorScheduler.

    def register_actor(self, actor_id_bytes: bytes, info: Dict[str, Any],
                       spec: Dict[str, Any], opts: Dict[str, Any]) -> None:
        actor_id = ActorID(actor_id_bytes)
        with self._lock:
            # Idempotent per actor id: the creator's client retries through
            # controller restarts (ReconnectingClient), so a re-delivered
            # registration must not spawn a second scheduler thread or
            # trip the name-conflict check against itself.
            if actor_id in self._actors:
                return
            name = info.get("name")
            if name:
                existing = self._named_actors.get(name)
                if existing is not None and existing != actor_id:
                    rec = self._actors.get(existing)
                    if rec is not None and rec.state != DEAD:
                        raise ValueError(
                            f"Actor with name {name!r} already exists")
                self._named_actors[name] = actor_id
            rec = ActorRecord(actor_id, info, spec, opts)
            self._actors[actor_id] = rec
            self._publish_actor(rec)
        threading.Thread(target=self._schedule_actor, args=(actor_id,),
                         name="actor-schedule", daemon=True).start()

    def _schedule_actor(self, actor_id: ActorID) -> None:
        """Place the actor on a node, lease a dedicated worker, push
        ``__init__`` (reference: GcsActorScheduler lease-based scheduling)."""
        with self._lock:
            rec = self._actors.get(actor_id)
            if rec is None or rec.state == DEAD:
                return
            opts = rec.opts
            spec = dict(rec.spec)
            incarnation = rec.incarnation
        t_sched = time.perf_counter()
        try:
            deadline = time.monotonic() + config.worker_lease_timeout_s
            excluded: List[bytes] = []
            while True:
                placement = opts.get("placement")
                picked_node_id = None
                if placement is not None:
                    pg = self.get_placement_group(placement[0])
                    if pg is None or placement[1] not in pg["placement"]:
                        raise RuntimeError(
                            f"placement group bundle {placement} not ready")
                    node_id_bytes, node_addr = pg["placement"][placement[1]]
                    bundle = (placement[0], placement[1])
                else:
                    pick = self.pick_node(
                        opts.get("resources", {"CPU": 1.0}),
                        opts.get("scheduling_strategy"), None, excluded)
                    if pick is None:
                        if time.monotonic() > deadline:
                            raise RuntimeError(
                                f"no feasible node for actor resources "
                                f"{opts.get('resources')}")
                        time.sleep(0.2)
                        excluded = []
                        continue
                    node_addr, node_id_bytes = pick["addr"], pick["node_id"]
                    picked_node_id = node_id_bytes
                    bundle = None
                try:
                    lease = NodeStub(
                        self._clients.get(tuple(node_addr))
                    ).create_actor_worker(
                        opts.get("resources", {"CPU": 1.0}), bundle, None,
                        opts.get("runtime_env"),
                        timeout=config.worker_lease_timeout_s + 10.0)
                except Exception as e:
                    self._clients.invalidate(tuple(node_addr))
                    lease = {"error": f"node unreachable: {e}"}
                if "error" in lease:
                    if picked_node_id is not None:
                        excluded.append(picked_node_id)
                    if lease.get("permanent") or time.monotonic() > deadline:
                        raise RuntimeError(
                            f"actor worker lease failed: {lease['error']}")
                    # PG-bundle leases skip pick_node, so back off here too —
                    # otherwise this loop busy-spins RPCs at a busy node.
                    time.sleep(0.2)
                    continue
                worker_addr = tuple(lease["addr"])
                reply = CoreWorkerStub(
                    self._clients.get(worker_addr)).start_actor(
                        spec, timeout=None)
                if reply["ok"]:
                    raced = False
                    with self._lock:
                        rec = self._actors.get(actor_id)
                        if rec is None or rec.incarnation != incarnation \
                                or rec.state == DEAD:
                            # Raced with kill/another restart: release
                            # the worker — but OUTSIDE self._lock. The
                            # kill_worker RPC has no timeout, and _lock
                            # guards ALL controller state: a slow node
                            # here would stall every heartbeat/lease/
                            # kill in the control plane behind this one
                            # cleanup (graftlint: lock-held-blocking).
                            raced = True
                        else:
                            rec.state = ALIVE
                            rec.addr = (worker_addr, lease["worker_id"],
                                        tuple(node_addr))
                            rec.node_id = NodeID(node_id_bytes)
                            self._publish_actor(rec)
                    if raced:
                        NodeStub(self._clients.get(
                            tuple(node_addr))).kill_worker(
                                lease["worker_id"], True)
                    elif config.core_metrics_enabled:
                        from ray_tpu.core import coremetrics as cm

                        # Lease-grant latency pick -> ALIVE (scheduler
                        # thread, not the reactor).
                        cm.CTRL_SCHEDULE_S.observe(
                            time.perf_counter() - t_sched)
                    return
                # __init__ raised: permanent failure, no restart (parity with
                # the reference: creation-task errors kill the actor).
                import pickle

                err_desc = "__init__ failed"
                try:
                    from ray_tpu.core import serialization

                    err = serialization.deserialize(reply["error_frame"])
                    err_desc = f"__init__ failed: {getattr(err, 'tb', err)}"
                except Exception:  # graftlint: disable=swallowed-exception
                    # Undeserializable error frame: the generic err_desc
                    # above already tells the caller WHAT failed.
                    pass
                self._mark_dead_locked_safe(actor_id, err_desc)
                return
        except BaseException as e:  # noqa: BLE001
            self._mark_dead_locked_safe(actor_id, f"creation failed: {e!r}")

    def _mark_dead_locked_safe(self, actor_id: ActorID, reason: str) -> None:
        with self._lock:
            rec = self._actors.get(actor_id)
            if rec is not None:
                rec.state = DEAD
                rec.death_cause = reason
                self._record_actor_death(rec, reason, restarting=False)
                self._publish_actor(rec)

    @staticmethod
    def _record_actor_death(rec: ActorRecord, reason: str,
                            restarting: bool) -> None:
        """Flight-recorder witness of an actor death: the post-mortem's
        'who died, why, was it restarted' evidence (a SIGKILLed actor's
        own recorder can say nothing past its last flush)."""
        from ray_tpu.util import flightrec

        # Actor ids are the evidence here, not a label cardinality
        # hazard: the recorder is a bounded ring, not a registry.
        # graftlint: disable=metrics-label-cardinality
        flightrec.record("actor.death", actor=rec.actor_id.hex()[:8],
                         cls=str(rec.info.get("class_name", "")),
                         name=str(rec.info.get("name") or ""),
                         cause=reason, restarting=restarting)

    def report_actor_failure(self, actor_id_bytes: bytes,
                             reason: str = "") -> Dict[str, Any]:
        """A caller (or node-death handling) observed the actor's worker gone.
        Restart if budget remains (reference: max_restarts state machine,
        gcs_actor_manager.h:88); returns the resulting record."""
        actor_id = ActorID(actor_id_bytes)
        should_schedule = False
        with self._lock:
            rec = self._actors.get(actor_id)
            if rec is None:
                return None
            if rec.state in (DEAD, RESTARTING, PENDING_CREATION):
                return self._actor_summary(rec)
            max_restarts = rec.info.get("max_restarts", 0)
            if max_restarts == -1 or rec.num_restarts < max_restarts:
                rec.state = RESTARTING
                rec.num_restarts += 1
                rec.incarnation += 1
                rec.addr = None
                should_schedule = True
            else:
                rec.state = DEAD
                rec.death_cause = reason
            self._record_actor_death(rec, reason,
                                     restarting=should_schedule)
            self._publish_actor(rec)
            summary = self._actor_summary(rec)
        if should_schedule:
            def _delayed():
                time.sleep(config.actor_restart_delay_ms / 1000.0)
                self._schedule_actor(actor_id)

            threading.Thread(target=_delayed, name="actor-restart",
                             daemon=True).start()
        return summary

    def kill_actor(self, actor_id_bytes: bytes, no_restart: bool = True) -> None:
        actor_id = ActorID(actor_id_bytes)
        with self._lock:
            rec = self._actors.get(actor_id)
            if rec is None or rec.state == DEAD:
                return
            addr = rec.addr
            if no_restart:
                rec.state = DEAD
                rec.death_cause = "killed via kill()"
                self._record_actor_death(rec, rec.death_cause,
                                         restarting=False)
                self._publish_actor(rec)
        if addr is not None:
            worker_addr, worker_id, node_addr = addr
            try:
                NodeStub(self._clients.get(
                    tuple(node_addr))).kill_worker(
                        worker_id, True, timeout=5.0)
            except Exception:
                # The node may already be dead (its reaper got the
                # worker); a live node failing kills leaks workers.
                log_every("controller.kill_worker", 10.0, logger,
                          "kill_worker for actor kill failed",
                          exc_info=True)
        if not no_restart:
            self.report_actor_failure(actor_id_bytes, "killed (restartable)")

    def _publish_actor(self, rec: ActorRecord) -> None:
        """Push the actor's new state to long-poll subscribers (reference:
        GCS actor channel, pubsub.proto GCS_ACTOR_CHANNEL)."""
        self.pubsub.publish("actors", rec.actor_id.hex(),
                            self._actor_summary(rec))

    def _actor_summary(self, rec: ActorRecord) -> Dict[str, Any]:
        return {
            "actor_id": rec.actor_id.binary(),
            "state": rec.state,
            "addr": rec.addr,
            "node_id": rec.node_id.binary() if rec.node_id else None,
            "info": rec.info,
            "num_restarts": rec.num_restarts,
            "incarnation": rec.incarnation,
            "death_cause": rec.death_cause,
        }

    def get_actor(self, actor_id_bytes: bytes) -> Optional[Dict[str, Any]]:
        with self._lock:
            rec = self._actors.get(ActorID(actor_id_bytes))
            return None if rec is None else self._actor_summary(rec)

    def list_actors(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [self._actor_summary(r) for r in self._actors.values()]

    def get_named_actor(self, name: str) -> Optional[bytes]:
        with self._lock:
            actor_id = self._named_actors.get(name)
            return actor_id.binary() if actor_id else None

    # ---------------------------------------------------------------- kv

    def kv_put(self, key: str, value: bytes, overwrite: bool = True) -> bool:
        with self._lock:
            if not overwrite and key in self._kv:
                return False
            self._kv[key] = value
        self.pubsub.publish("kv", key, None)
        return True

    def kv_get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._kv.get(key)

    def kv_del(self, key: str) -> bool:
        with self._lock:
            return self._kv.pop(key, None) is not None

    # Epoch leases: named monotonic counters living IN the KV (so they
    # persist with it and a replacement head keeps fencing honest).
    # ``epoch_bump`` is the lease acquisition a process takes when it
    # claims a singleton role (the serve controller on every (re)start);
    # ``kv_put_fenced`` is the write path that role's state goes through
    # — a writer whose epoch is no longer the newest is a ZOMBIE (its
    # replacement already bumped) and its write is rejected, not applied
    # (reference: GCS leader fencing; Serve's controller checkpoint has
    # exactly one legitimate writer at a time).

    @staticmethod
    def _epoch_key(name: str) -> str:
        return f"__epoch__:{name}"

    def epoch_bump(self, name: str) -> int:
        """Atomically increment and return the named epoch counter."""
        key = self._epoch_key(name)
        with self._lock:
            epoch = int(self._kv.get(key, b"0")) + 1
            self._kv[key] = str(epoch).encode()
        self.pubsub.publish("kv", key, None)
        return epoch

    def kv_put_fenced(self, key: str, value: bytes, epoch: int,
                      epoch_name: str) -> bool:
        """``kv_put`` gated on ``epoch`` still being the NEWEST bump of
        ``epoch_name``: returns False (no write) for a stale writer —
        the signal to self-fence and stop mutating."""
        with self._lock:
            current = int(self._kv.get(self._epoch_key(epoch_name), b"0"))
            if epoch < current:
                return False
            self._kv[key] = value
        self.pubsub.publish("kv", key, None)
        return True

    def kv_keys(self, prefix: str = "") -> List[str]:
        with self._lock:
            return [k for k in self._kv if k.startswith(prefix)]

    # -------------------------------------------------------------- jobs

    def register_job(self, job_id: str, info: Dict[str, Any]) -> None:
        with self._lock:
            self._jobs[job_id] = {"state": "RUNNING", **info}
        self.pubsub.publish("jobs", job_id, {"state": "RUNNING", **info})

    def finish_job(self, job_id: str, state: str = "SUCCEEDED") -> None:
        with self._lock:
            if job_id in self._jobs:
                self._jobs[job_id]["state"] = state
                info = dict(self._jobs[job_id])
            else:
                info = None
        if info is not None:
            self.pubsub.publish("jobs", job_id, info)

    def list_jobs(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return dict(self._jobs)

    # -------------------------------------------- placement groups (2PC)

    def create_placement_group(
        self,
        pg_id_bytes: bytes,
        bundles: List[Dict[str, float]],
        strategy: str,
    ) -> Dict[str, Any]:
        """Reserve all bundles atomically across nodes; idempotent.

        Two-phase commit as in the reference
        (``gcs_placement_group_scheduler.h`` + raylet
        ``placement_group_resource_manager.h``): phase 1 reserves each bundle
        on its chosen node (node-side reservation is idempotent per
        (pg, bundle)); if any reservation fails, prior reservations are rolled
        back and the PG returns to PENDING (caller may retry). Concurrent
        calls for the same PG observe CREATING and back off.
        """
        pg_id = PlacementGroupID(pg_id_bytes)
        with self._lock:
            rec = self._pgs.get(pg_id)
            if rec is None:
                rec = PlacementGroupRecord(pg_id, bundles, strategy)
                self._pgs[pg_id] = rec
            if rec.state == "CREATED":
                return self._pg_summary(rec)
            if rec.state == "CREATING":
                return {"state": "PENDING", "reason": "creation in progress"}
            rec.state = "CREATING"
            plan = self._plan_bundles(rec.bundles, rec.strategy)
        if plan is None:
            with self._lock:
                rec.state = "PENDING"
            return {"state": "PENDING", "reason": "infeasible"}
        reserved: List[Tuple[int, NodeRecord]] = []
        ok = True
        for idx, node_rec in plan:
            try:
                granted = NodeStub(
                    self._clients.get(node_rec.addr)).reserve_bundle(
                        pg_id_bytes, idx, rec.bundles[idx])
            except Exception:
                granted = False
            if granted:
                reserved.append((idx, node_rec))
            else:
                ok = False
                break
        if not ok:
            for idx, node_rec in reserved:
                try:
                    NodeStub(
                        self._clients.get(node_rec.addr)).release_bundle(
                            pg_id_bytes, idx)
                except Exception:
                    # A failed rollback strands the bundle's resources
                    # until the node re-registers — worth a trail.
                    log_every("controller.release_bundle", 10.0, logger,
                              "placement-group bundle rollback failed",
                              exc_info=True)
            with self._lock:
                rec.state = "PENDING"
            return {"state": "PENDING", "reason": "reservation_failed"}
        with self._lock:
            rec.state = "CREATED"
            for idx, node_rec in reserved:
                rec.placement[idx] = (node_rec.node_id, node_rec.addr)
                resmath.deduct(node_rec.available, rec.bundles[idx])
            summary = self._pg_summary(rec)
        self.pubsub.publish("placement_groups", rec.pg_id.hex(), summary)
        return summary

    def _plan_bundles(self, bundles, strategy):
        """Choose a node per bundle honoring PACK/SPREAD/STRICT_PACK/
        STRICT_SPREAD (reference: common.proto:937-944)."""
        alive = [r for r in self._nodes.values() if r.alive]
        if not alive:
            return None
        remaining = {r.node_id: dict(r.available) for r in alive}
        plan: List[Tuple[int, NodeRecord]] = []

        if strategy in ("STRICT_PACK", "PACK"):
            order = sorted(alive, key=lambda r: (-_utilization(r),
                                                 r.node_id.binary()))
            if strategy == "STRICT_PACK":
                for r in order:
                    rem = dict(r.available)
                    if all(resmath.take(rem, b) for b in bundles):
                        return [(i, r) for i in range(len(bundles))]
                return None
            for i, b in enumerate(bundles):
                placed = False
                for r in order:
                    if resmath.take(remaining[r.node_id], b):
                        plan.append((i, r))
                        placed = True
                        break
                if not placed:
                    return None
            return plan

        # SPREAD / STRICT_SPREAD: round-robin distinct nodes.
        order = sorted(alive, key=lambda r: (_utilization(r),
                                             r.node_id.binary()))
        used_nodes = set()
        for i, b in enumerate(bundles):
            placed = False
            candidates = [r for r in order if r.node_id not in used_nodes]
            if strategy == "SPREAD":
                candidates = candidates + [r for r in order
                                           if r.node_id in used_nodes]
            for r in candidates:
                if resmath.take(remaining[r.node_id], b):
                    plan.append((i, r))
                    used_nodes.add(r.node_id)
                    placed = True
                    break
            if not placed:
                return None
        return plan

    def _pg_summary(self, rec: PlacementGroupRecord) -> Dict[str, Any]:
        return {
            "pg_id": rec.pg_id.binary(),
            "state": rec.state,
            "strategy": rec.strategy,
            "bundles": rec.bundles,
            "placement": {i: (nid.binary(), addr)
                          for i, (nid, addr) in rec.placement.items()},
        }

    def get_placement_group(self, pg_id_bytes: bytes) -> Optional[Dict[str, Any]]:
        with self._lock:
            rec = self._pgs.get(PlacementGroupID(pg_id_bytes))
            return self._pg_summary(rec) if rec else None

    def remove_placement_group(self, pg_id_bytes: bytes) -> None:
        with self._lock:
            rec = self._pgs.pop(PlacementGroupID(pg_id_bytes), None)
        if rec is None:
            return
        for idx, (node_id, addr) in rec.placement.items():
            try:
                NodeStub(self._clients.get(addr)).release_bundle(
                    pg_id_bytes, idx)
            except Exception:
                log_every("controller.release_bundle", 10.0, logger,
                          "placement-group bundle release failed",
                          exc_info=True)
            with self._lock:
                node_rec = self._nodes.get(node_id)
                if node_rec is not None:
                    resmath.credit(node_rec.available, rec.bundles[idx])

    def autoscaler_state(self) -> Dict[str, Any]:
        """Load view for the autoscaler (reference: autoscaler.proto
        GetClusterResourceState): alive nodes + live unmet demand (entries expire 10s after failures stop)."""
        cutoff = time.monotonic() - 10.0
        with self._lock:
            self._pending_demand = {
                k: entry for k, entry in self._pending_demand.items()
                if entry[1] > cutoff}
            return {
                "nodes": [r.summary() for r in self._nodes.values()],
                "pending_demand": [
                    {"resources": s, "labels": labels}
                    for s, _ts, labels in self._pending_demand.values()],
                # Autopilot-demoted hosts: the autoscaler must not let a
                # demoted host's free capacity mark demand as met (it
                # should launch a healthy replacement instead).
                "tainted": sorted(self._topology.tainted()),
            }

    # ------------------------------------------- metrics + task events
    #
    # Observability floor (reference: src/ray/stats/metric_defs.cc export
    # pipeline + GcsTaskManager's bounded task-event store,
    # gcs_task_manager.h:80). Workers push; the controller aggregates and
    # serves the state API / Prometheus text / timeline dump.

    def push_metrics(self, source: Dict[str, Any],
                     snapshot: List[Dict[str, Any]]) -> None:
        """Latest CUMULATIVE snapshot per source process, keyed
        "<node8>/<role>/pid<N>" (node prefix lets node death drop the
        series; role lets Prometheus queries split control/data plane).
        Replacement — never accumulation — is what makes restarts and
        missed pushes safe."""
        key = (f"{NodeID(source['node_id']).hex()[:8]}/"
               f"{source.get('role', 'worker')}/"
               f"pid{source.get('pid', 0)}")
        with self._lock:
            self._metrics[key] = snapshot
            self._metrics_ts[key] = time.monotonic()

    def list_metrics(self) -> Dict[str, List[Dict[str, Any]]]:
        with self._lock:
            return {k: list(v) for k, v in self._metrics.items()}

    def metrics_text(self) -> str:
        from ray_tpu.util.metrics import prometheus_text

        return prometheus_text(self.list_metrics())

    def push_task_events(self, events: List[Dict[str, Any]]) -> None:
        cap = config.event_buffer_max
        with self._lock:
            self._task_events.extend(events)
            if len(self._task_events) > cap:
                # Bounded, priority to the newest (gcs_task_manager evicts
                # oldest first the same way).
                del self._task_events[:len(self._task_events) - cap]

    def list_task_events(self, limit: int = 1000) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._task_events[-limit:])

    def fr_dump(self, max_age_s: float = 0.0) -> Dict[str, Any]:
        """Merged flight-recorder dumps from every process on this host
        (util/flightrec.py): the controller flushes its own ring, then
        reads each persisted fr-<pid>.json under flightrec_dir —
        including files left by processes that are already dead, which
        is the whole point (`ray_tpu doctor --post-mortem` reads this).
        ``max_age_s`` > 0 drops files whose last flush is older (stale
        sessions on a shared dir)."""
        from ray_tpu.util import flightrec

        flightrec.flush_now()
        return flightrec.dump_all(
            max_age_s=max_age_s if max_age_s > 0 else None)

    # ----------------------------------------------------------- control

    def stop(self) -> None:
        self._stopped.set()
        if self._metrics_http is not None:
            try:
                self._metrics_http.shutdown()
                self._metrics_http.server_close()
            except Exception:  # graftlint: disable=swallowed-exception
                # Teardown-only: the daemon thread dies with the process.
                pass
        try:
            self.save_state()
        except Exception:
            # Failing to persist at shutdown means the next head start
            # comes up empty — never silent.
            logger.warning("controller state save on stop failed",
                           exc_info=True)
        self._clients.close_all()
        self._server.stop()
