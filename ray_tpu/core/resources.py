"""Resource-set arithmetic shared by controller and node.

Analogue of the reference's resource model (``src/ray/common/scheduling/
resource_instance_set.h`` + ``fixed_point.h``): the reference uses fixed-point
integers to avoid float drift; here a single epsilon-tolerant helper set keeps
controller and node feasibility decisions consistent (one definition, not
four).
"""

from __future__ import annotations

from typing import Dict, Optional

EPS = 1e-9

# Accelerator-count keys in the resource vector. ``CHIPS`` is the
# node-level accelerator count (TPU chips, or virtual devices on the dev
# box); ``slice:<id>`` keys bind that count to a named pod slice so a
# mesh-parallel replica's reservation is accounted against the RIGHT
# slice, not a pooled cluster total. Both are plain scalar resources —
# fits/take/credit below need no special cases (the whole point of
# keeping the vector a flat dict).
CHIPS = "chips"
SLICE_PREFIX = "slice:"


def chip_count(res: Dict[str, float]) -> float:
    """Accelerator chips in a resource vector (0.0 when none)."""
    return res.get(CHIPS, 0.0)


def slice_key(slice_id: str) -> str:
    return SLICE_PREFIX + slice_id


def slice_of(res: Dict[str, float]) -> Optional[str]:
    """The slice id a resource vector is bound to (first ``slice:`` key),
    or None for slice-agnostic vectors."""
    for k in res:
        if k.startswith(SLICE_PREFIX):
            return k[len(SLICE_PREFIX):]
    return None


def chip_resources(chips: float,
                   slice_id: Optional[str] = None) -> Dict[str, float]:
    """Resource vector for ``chips`` accelerators, optionally bound to a
    slice (what a sub-slice replica requests and a node advertises)."""
    out = {CHIPS: float(chips)}
    if slice_id:
        out[slice_key(slice_id)] = float(chips)
    return out


def fits(avail: Dict[str, float], req: Dict[str, float]) -> bool:
    """True if ``req`` fits in ``avail`` (missing keys = 0)."""
    return all(avail.get(k, 0.0) + EPS >= v for k, v in req.items())


def take(avail: Dict[str, float], req: Dict[str, float]) -> bool:
    """Atomically deduct ``req`` from ``avail`` if it fits. Caller holds the
    lock protecting ``avail``."""
    if not fits(avail, req):
        return False
    for k, v in req.items():
        avail[k] = avail.get(k, 0.0) - v
    return True


def deduct(avail: Dict[str, float], req: Dict[str, float]) -> None:
    """Deduct without a feasibility check (optimistic accounting)."""
    for k, v in req.items():
        avail[k] = avail.get(k, 0.0) - v


def credit(avail: Dict[str, float], req: Dict[str, float]) -> None:
    for k, v in req.items():
        avail[k] = avail.get(k, 0.0) + v
