"""Resource-set arithmetic shared by controller and node.

Analogue of the reference's resource model (``src/ray/common/scheduling/
resource_instance_set.h`` + ``fixed_point.h``): the reference uses fixed-point
integers to avoid float drift; here a single epsilon-tolerant helper set keeps
controller and node feasibility decisions consistent (one definition, not
four).
"""

from __future__ import annotations

from typing import Dict

EPS = 1e-9


def fits(avail: Dict[str, float], req: Dict[str, float]) -> bool:
    """True if ``req`` fits in ``avail`` (missing keys = 0)."""
    return all(avail.get(k, 0.0) + EPS >= v for k, v in req.items())


def take(avail: Dict[str, float], req: Dict[str, float]) -> bool:
    """Atomically deduct ``req`` from ``avail`` if it fits. Caller holds the
    lock protecting ``avail``."""
    if not fits(avail, req):
        return False
    for k, v in req.items():
        avail[k] = avail.get(k, 0.0) - v
    return True


def deduct(avail: Dict[str, float], req: Dict[str, float]) -> None:
    """Deduct without a feasibility check (optimistic accounting)."""
    for k, v in req.items():
        avail[k] = avail.get(k, 0.0) - v


def credit(avail: Dict[str, float], req: Dict[str, float]) -> None:
    for k, v in req.items():
        avail[k] = avail.get(k, 0.0) + v
