"""Worker forkserver: pre-imported template process that ``os.fork()``s
warm workers on demand.

TPU-era answer to the reference's prestarted worker pool
(``src/ray/raylet/worker_pool.h:357`` ``PrestartWorkers`` +
``StartWorkerProcess`` ``worker_pool.h:423``): instead of paying interpreter
startup + imports per worker process (~150 ms CPU on this box, ~2 s when the
accelerator site hook imports jax), the node supervisor starts ONE template
process that imports the worker hot path once, then forks children in
~10 ms each. Children inherit the warm import state copy-on-write and jump
straight into ``worker_main.run``.

Why a custom forkserver rather than ``multiprocessing``'s: the child must
exec nothing (keeping the warm imports is the whole point), must re-point
stdout/stderr at per-worker session log files before any user code runs, and
must stay attached to the node's registration/ping protocol — all of which
is a 30-line ``os.fork`` away here and fights the stdlib harness otherwise.

Protocol (stdin/stdout of the template, length-prefixed pickle):
  request  {"worker_id": hex, "env": {str: str}, "stdout": path|None,
            "stderr": path|None}
  reply    {"pid": int} | {"error": str}

The template is SINGLE-THREADED (fork in a threaded process deadlocks
arbitrary locks); it reaps dead children via SIGCHLD so the node never
accumulates zombies, and exits when its stdin closes (node death — the same
orphan protection workers get from their node ping loop).

Fork-safety note: children MUST NOT inherit the template's signal handler —
they restore default SIGCHLD before running, or CoreWorker subprocesses
(none today, but spill helpers may come) would be mis-reaped.
"""

from __future__ import annotations

import os
import pickle
import signal
import struct
import sys


def _read_msg(f):
    header = f.read(4)
    if len(header) < 4:
        return None
    (n,) = struct.unpack("!I", header)
    body = f.read(n)
    if len(body) < n:
        return None
    return pickle.loads(body)


def _write_msg(f, obj) -> None:
    blob = pickle.dumps(obj, protocol=5)
    f.write(struct.pack("!I", len(blob)) + blob)
    f.flush()


def _reap(_signum, _frame) -> None:
    try:
        while True:
            pid, _ = os.waitpid(-1, os.WNOHANG)
            if pid <= 0:
                break
    except OSError:
        pass


def _child(req, node_addr, controller_addr, node_id_hex: str) -> "int":
    signal.signal(signal.SIGCHLD, signal.SIG_DFL)
    os.environ.update(req.get("env") or {})
    # Per-worker session log files, wired before ANY output (the log
    # monitor tails these; reference: default_worker.py stdout/stderr
    # redirection under session_latest/logs).
    for path, fd in ((req.get("stdout"), 1), (req.get("stderr"), 2)):
        if path:
            log_fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                             0o644)
            os.dup2(log_fd, fd)
            os.close(log_fd)
    if not req.get("stdout"):
        # fd 1 is the template's REPLY PIPE — a stray user print would
        # corrupt the fork protocol. Point it wherever stderr goes.
        os.dup2(2, 1)
    # fd 0 is the template's REQUEST PIPE: user code reading stdin would
    # race the template and eat fork-request bytes.
    null_fd = os.open(os.devnull, os.O_RDONLY)
    os.dup2(null_fd, 0)
    os.close(null_fd)
    sys.stdout = os.fdopen(1, "w", buffering=1, closefd=False)
    sys.stderr = os.fdopen(2, "w", buffering=1, closefd=False)
    from ray_tpu.core import worker_main

    return worker_main.run(node_addr, controller_addr, node_id_hex,
                           req["worker_id"])


def main() -> int:
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--node-host", required=True)
    parser.add_argument("--node-port", type=int, required=True)
    parser.add_argument("--controller-host", required=True)
    parser.add_argument("--controller-port", type=int, required=True)
    parser.add_argument("--node-id", required=True)
    args = parser.parse_args()
    node_addr = (args.node_host, args.node_port)
    controller_addr = (args.controller_host, args.controller_port)

    # Warm the import state children will inherit copy-on-write. Everything
    # a CoreWorker touches before its first task; NOT jax (CPU workers
    # never need it and the accelerator env is stripped by the node).
    from ray_tpu.core import runtime, serialization  # noqa: F401
    from ray_tpu.core import object_store, rpc, ids  # noqa: F401

    signal.signal(signal.SIGCHLD, _reap)
    stdin = sys.stdin.buffer
    stdout = sys.stdout.buffer
    while True:
        try:
            req = _read_msg(stdin)
        except (OSError, EOFError, pickle.UnpicklingError):
            break
        if req is None:  # stdin closed: node is gone
            break
        try:
            pid = os.fork()
        except OSError as e:
            _write_msg(stdout, {"error": f"fork failed: {e}"})
            continue
        if pid == 0:
            code = 1
            try:
                code = _child(req, node_addr, controller_addr, args.node_id)
            except BaseException:
                import traceback

                traceback.print_exc()
            finally:
                # Skip atexit/gc of inherited state: exit NOW, flushing only
                # this child's own streams.
                try:
                    sys.stdout.flush()
                    sys.stderr.flush()
                except Exception:  # graftlint: disable=swallowed-exception
                    # About to os._exit inside a forked child: nothing
                    # to report to, nowhere to report.
                    pass
                os._exit(code)
        _write_msg(stdout, {"pid": pid})
    return 0


if __name__ == "__main__":
    sys.exit(main())
