"""Actors: stateful workers with ordered method invocation.

Analogue of the reference's ``python/ray/actor.py`` frontend over the GCS
actor lifecycle (``gcs_actor_manager.cc``: register -> schedule -> ALIVE ->
RESTARTING/DEAD) and the direct actor transport
(``direct_actor_task_submitter.h:74``: per-caller sequence numbers, direct
push to the actor's worker). Creation and restarts are driven by the
controller (as in the reference, where the GCS owns actor scheduling);
the handle is usable immediately — method calls block on ALIVE, and creation
errors surface as ``ActorDiedError`` carrying the ``__init__`` traceback.

Restart semantics (``max_restarts``): when a caller observes the actor's
worker unreachable it reports the failure; the controller either restarts
(incrementing the *incarnation*) or marks the actor DEAD. In-flight calls to
the dead incarnation fail with ``ActorUnavailableError``; the caller's
sequence stream resets for the new incarnation.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from ray_tpu.core import serialization
from ray_tpu.core.controller import ALIVE, DEAD, RESTARTING
from ray_tpu.core.errors import ActorDiedError, ActorUnavailableError
from ray_tpu.core.ids import ActorID, ObjectID, TaskID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.remote_function import (
    _normalized_env,
    _placement_tuple,
    _resources_from_options,
    _strategy_dict,
    export_callable,
)
from ray_tpu.core.rpc import RemoteCallError, RpcError
from ray_tpu.core.runtime import get_core_worker

# Per-process submission sequence numbers, keyed by (actor, incarnation) so a
# restarted actor sees a fresh seq stream from each caller.
_seq_counters: Dict[tuple, int] = {}
_seq_lock = threading.Lock()
# Per-actor cap on in-flight pushes so out-of-order arrivals can't exhaust the
# actor server's handler pool (reference: max_pending_calls).
_inflight: Dict[ActorID, threading.Semaphore] = {}


def _next_seq(actor_id: ActorID, incarnation: int) -> int:
    with _seq_lock:
        key = (actor_id, incarnation)
        seq = _seq_counters.get(key, 0)
        _seq_counters[key] = seq + 1
        return seq


def _inflight_sem(actor_id: ActorID) -> threading.Semaphore:
    with _seq_lock:
        sem = _inflight.get(actor_id)
        if sem is None:
            sem = threading.Semaphore(32)
            _inflight[actor_id] = sem
        return sem


class ActorClass:
    def __init__(self, cls, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = dict(options or {})
        self.__name__ = getattr(cls, "__name__", "ActorClass")

    def options(self, **overrides) -> "ActorClass":
        merged = dict(self._options)
        merged.update(overrides)
        return ActorClass(self._cls, merged)

    def remote(self, *args, **kwargs) -> "ActorHandle":
        core = get_core_worker()
        opts = self._options
        actor_id = ActorID.from_random()
        cls_key, _ = export_callable(self._cls)
        resources = _resources_from_options(opts)
        info = {
            "name": opts.get("name"),
            "class_name": self.__name__,
            "resources": resources,
            "max_restarts": opts.get("max_restarts", 0),
            "cls_key": cls_key,
        }
        spec = {
            "cls_key": cls_key,
            "desc": self.__name__,
            "args_blob": serialization.serialize((args, kwargs)),
            "max_concurrency": opts.get("max_concurrency", 1),
        }
        creation_opts = {
            "resources": resources,
            "scheduling_strategy": _strategy_dict(opts.get("scheduling_strategy")),
            "placement": _placement_tuple(opts),
            "runtime_env": _normalized_env(opts),
        }
        core.controller.call("register_actor", actor_id.binary(), info,
                             spec, creation_opts)
        return ActorHandle(actor_id)


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str,
                 num_returns: int = 1):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns

    def options(self, num_returns: int = 1) -> "ActorMethod":
        return ActorMethod(self._handle, self._name, num_returns)

    def remote(self, *args, **kwargs):
        return self._handle._submit(self._name, args, kwargs,
                                    self._num_returns)


class ActorHandle:
    def __init__(self, actor_id: ActorID):
        self._actor_id = actor_id
        self._cached: Optional[Dict[str, Any]] = None
        # Last incarnation this process observed; new submissions open their
        # seq stream against it so a restarted actor sees seqs from 0.
        self._known_inc = 0

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id

    def __reduce__(self):
        return (ActorHandle, (self._actor_id,))

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()})"

    def _resolve(self, timeout: float = 60.0) -> Dict[str, Any]:
        """Wait until the actor is ALIVE; raise ActorDiedError if DEAD.

        Push-driven: one synchronous read, then a long-poll subscription on
        the controller's actor channel (reference: GCS actor pubsub replacing
        WaitForActorRefDeleted-style polling; serve long_poll.py:173)."""
        cached = self._cached
        if cached is not None:
            return cached
        core = get_core_worker()
        record = core.controller.call("get_actor", self._actor_id.binary())
        if record is None:
            raise ActorDiedError(self._actor_id, "unknown actor")
        deadline = time.monotonic() + timeout
        version = 0
        while True:
            if record["state"] == ALIVE:
                self._cached = record
                self._known_inc = max(self._known_inc, record["incarnation"])
                return record
            if record["state"] == DEAD:
                raise ActorDiedError(self._actor_id,
                                     record.get("death_cause") or "")
            step = min(10.0, deadline - time.monotonic())
            if step <= 0:
                raise ActorDiedError(
                    self._actor_id,
                    f"actor stuck in state {record['state']} for {timeout}s")
            try:
                update = core.controller.call(
                    "psub_poll", "actors", self._actor_id.hex(), version,
                    step, timeout=step + 15.0)
            except (RpcError, TimeoutError):
                # A slow/saturated controller long-poll is NOT an actor
                # failure (the caller's except-branch would misclassify it
                # and restart a healthy actor): degrade to a plain re-read.
                time.sleep(0.2)
                update = None
            if update is None:  # long-poll timed out: re-read and loop
                record = core.controller.call(
                    "get_actor", self._actor_id.binary())
                if record is None:
                    raise ActorDiedError(self._actor_id, "unknown actor")
                continue
            version, record = update

    def _incarnation_hint(self) -> int:
        return self._known_inc

    def _submit(self, method: str, args: tuple, kwargs: dict,
                num_returns: int) -> Any:
        core = get_core_worker()
        return_ids = [ObjectID.from_random() for _ in range(num_returns)]
        refs = [ObjectRef(oid, core.addr) for oid in return_ids]
        for oid in return_ids:
            core.store.create_pending(oid)
        # Seq allocated synchronously (submission order) against the caller's
        # current view of the incarnation; a stale view is healed by the
        # actor-side bounded gap wait plus the reset below.
        incarnation = self._incarnation_hint()
        seq = _next_seq(self._actor_id, incarnation)
        with serialization.capture_refs() as held_refs:
            args_blob = serialization.serialize((args, kwargs))
        spec = {
            "task_id": TaskID.from_random().binary(),
            "method": method,
            "desc": f"{self._actor_id.hex()[:8]}.{method}",
            "args_blob": args_blob,
            "return_ids": [o.binary() for o in return_ids],
            "owner_addr": core.addr,
            "seq": seq,
            "epoch": incarnation,
        }
        from ray_tpu.util import tracing

        trace_ctx = tracing.context_for_spec()
        if trace_ctx is not None:
            spec["trace"] = trace_ctx
        from ray_tpu.core.runtime import _collect_top_level_refs

        arg_refs = _collect_top_level_refs(args, kwargs)
        sem = _inflight_sem(self._actor_id)
        core.submitter._pool.submit(
            self._push, core, spec, return_ids, arg_refs, sem, held_refs)
        if num_returns == 0:
            return None
        if num_returns == 1:
            return refs[0]
        return refs

    def _push(self, core, spec, return_ids, arg_refs, sem,
              held_refs=None) -> None:
        # held_refs keeps every ref pickled into the args alive (handles
        # registered) for the in-flight window; see TaskSubmitter.submit.
        try:
            for ref in arg_refs:
                core.wait_ready(ref, None)
            record = self._resolve()
            if record["incarnation"] != spec["epoch"]:
                # Submitted against an incarnation that died before the push:
                # the call is lost (reference: in-flight actor tasks are not
                # transparently retried across restarts by default).
                raise _StaleEpoch(record["incarnation"])
            worker_addr = tuple(record["addr"][0])
            sem.acquire()
            try:
                reply = core.clients.get(worker_addr).call(
                    "push_actor_task", spec, timeout=None)
            finally:
                sem.release()
            if reply["ok"]:
                for oid, packed in zip(return_ids, reply["results"]):
                    core.fulfil_result(oid, packed)
            else:
                for oid in return_ids:
                    core.store.put_serialized(oid, reply["error_frame"])
        except _StaleEpoch as e:
            self._known_inc = max(self._known_inc, e.incarnation)
            err = ActorUnavailableError(
                f"actor {self._actor_id.hex()} restarted before this call "
                f"was delivered; resubmit")
            for oid in return_ids:
                core.store.put_error(oid, err)
        except (RpcError, RemoteCallError, TimeoutError) as e:
            # Worker unreachable: report to the controller, which restarts
            # (new incarnation) or declares the actor dead.
            self._cached = None
            err: BaseException
            try:
                record = core.controller.call(
                    "report_actor_failure", self._actor_id.binary(),
                    f"worker unreachable: {e}")
            except Exception:
                record = None
            if record is not None:
                self._known_inc = max(self._known_inc, record["incarnation"])
            if record is not None and record["state"] in (RESTARTING, ALIVE):
                err = ActorUnavailableError(
                    f"actor {self._actor_id.hex()} restarting; call lost: {e}")
            else:
                err = ActorDiedError(self._actor_id, f"actor task failed: {e}")
            for oid in return_ids:
                core.store.put_error(oid, err)
        except BaseException as e:  # noqa: BLE001
            for oid in return_ids:
                core.store.put_error(oid, e)

    def kill(self, no_restart: bool = True) -> None:
        core = get_core_worker()
        self._cached = None
        core.controller.call("kill_actor", self._actor_id.binary(), no_restart)


class _StaleEpoch(Exception):
    def __init__(self, incarnation: int):
        self.incarnation = incarnation
        super().__init__(f"stale epoch; current incarnation {incarnation}")


def get_actor(name: str) -> ActorHandle:
    """Look up a named actor (reference: ``ray.get_actor``)."""
    core = get_core_worker()
    actor_id_bytes = core.controller.call("get_named_actor", name)
    if actor_id_bytes is None:
        raise ValueError(f"no actor named {name!r}")
    return ActorHandle(ActorID(actor_id_bytes))
