"""Versioned long-poll pub/sub hub.

TPU-era analogue of the reference's two notification layers: the generalized
pubsub used for GCS notifications (``src/ray/pubsub/publisher.h`` — one
long-poll connection per subscriber, batched messages) and Serve's
``LongPollHost`` (``serve/_private/long_poll.py:173`` — versioned snapshots,
subscribers re-poll with the last version they saw). The hub keeps only the
LATEST value per (channel, key) with a monotonically increasing version —
subscribers that fall behind see the newest state, not an event log, which is
the right semantics for control-plane state (actor records, serve configs,
job states) and keeps memory bounded.

Embedded in the controller (server side) and wrapped by :class:`Subscriber`
(client side). Wakeups are condition-variable broadcast; a poll with an
up-to-date version parks until publish or timeout.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)


class Pubsub:
    def __init__(self):
        self._cond = threading.Condition()
        # (channel, key) -> (version, value). Versions are per-(channel,key).
        self._state: Dict[Tuple[str, str], Tuple[int, Any]] = {}
        # (channel, key) -> monotonic publish time of the CURRENT version
        # (publish -> deliver latency; guarded by _cond).
        self._pub_ts: Dict[Tuple[str, str], float] = {}
        # (channel, key) -> highest publisher epoch seen (guarded by
        # _cond). Keys published WITH an epoch are fenced: a later
        # publish carrying a lower epoch is rejected — the zombie-old-
        # controller write the serve plane's restart protocol must
        # exclude (reference: GCS leader fencing via Redis epochs).
        self._pub_epochs: Dict[Tuple[str, str], int] = {}

    @staticmethod
    def _instrumented() -> bool:
        from ray_tpu.core.config import config

        return config.core_metrics_enabled

    def _observe_delivery(self, channel: str, cur: Tuple[int, Any],
                          last_version: int, pub_ts: Optional[float],
                          parked_since: float) -> None:
        """Record subscriber lag (versions skipped by this poll) and, for
        a poller that was PARKED when the publish landed, the publish ->
        delivery latency. Runs on RPC pool threads, never the reactor."""
        from ray_tpu.core import coremetrics as cm

        tags = {"channel": channel}
        cm.PSUB_SUB_LAG.observe(float(cur[0] - last_version), tags)
        if pub_ts is not None and pub_ts >= parked_since:
            cm.PSUB_DELIVER_S.observe(time.monotonic() - pub_ts, tags)

    def publish(self, channel: str, key: str, value: Any,
                min_version: int = 0,
                epoch: Optional[int] = None) -> Optional[int]:
        """``min_version`` lets a publisher keep its subscribers' version
        clocks monotonic across a HUB restart (head FT): a fresh hub would
        restart at 1, below what long-pollers already saw, stranding them —
        the publisher passes the floor it knows it reached before.

        ``epoch`` opts the key into publisher FENCING: the hub remembers
        the highest epoch that published it, and a publish carrying a
        LOWER epoch returns None without writing — a deposed serve
        controller (its replacement bumped the epoch) cannot clobber the
        live snapshot, however late its write arrives. Epoch-less
        publishes on the same key stay unfenced (back-compat)."""
        instrumented = self._instrumented()
        with self._cond:
            if epoch is not None:
                cur_epoch = self._pub_epochs.get((channel, key), 0)
                if epoch < cur_epoch:
                    return None  # fenced: a newer publisher owns the key
                self._pub_epochs[(channel, key)] = epoch
            version = max(self._state.get((channel, key), (0, None))[0] + 1,
                          min_version)
            self._state[(channel, key)] = (version, value)
            if instrumented:
                self._pub_ts[(channel, key)] = time.monotonic()
            self._cond.notify_all()
        if instrumented:
            from ray_tpu.core import coremetrics as cm

            cm.PSUB_PUBLISHES.inc(1.0, {"channel": channel})
        return version

    def drop(self, channel: str, key: str) -> None:
        with self._cond:
            self._state.pop((channel, key), None)
            self._pub_ts.pop((channel, key), None)
            self._pub_epochs.pop((channel, key), None)

    def poll(self, channel: str, key: str, last_version: int = 0,
             timeout: float = 30.0) -> Optional[Tuple[int, Any]]:
        """Long-poll: block until (channel, key) has a version newer than
        ``last_version``; returns (version, value) or None on timeout."""
        t_parked = time.monotonic()
        deadline = t_parked + timeout
        instrumented = self._instrumented()
        with self._cond:
            while True:
                cur = self._state.get((channel, key))
                if cur is not None and cur[0] > last_version:
                    pub_ts = self._pub_ts.get((channel, key))
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cond.wait(min(remaining, 1.0))
        if instrumented:
            self._observe_delivery(channel, cur, last_version, pub_ts,
                                   t_parked)
        return cur

    def poll_many(self, watches: Dict[str, Tuple[str, str, int]],
                  timeout: float = 30.0):
        """Multi-key long-poll (Serve's LongPollHost shape): ``watches`` maps
        a caller-chosen tag -> (channel, key, last_version). Returns
        {tag: (version, value)} for every watch that has news, or None on
        timeout. One condition wait covers all watches."""
        t_parked = time.monotonic()
        deadline = t_parked + timeout
        instrumented = self._instrumented()
        with self._cond:
            while True:
                updates = {}
                meta = []
                for tag, (channel, key, last) in watches.items():
                    cur = self._state.get((channel, key))
                    if cur is not None and cur[0] > last:
                        updates[tag] = cur
                        meta.append((channel, cur, last,
                                     self._pub_ts.get((channel, key))))
                if updates:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cond.wait(min(remaining, 1.0))
        if instrumented:
            for channel, cur, last, pub_ts in meta:
                self._observe_delivery(channel, cur, last, pub_ts, t_parked)
        return updates

    def snapshot(self, channel: str) -> Dict[str, Tuple[int, Any]]:
        with self._cond:
            return {k: v for (ch, k), v in self._state.items()
                    if ch == channel}

    def keys(self, channel: str) -> Dict[str, int]:
        """Key -> current version for a channel, without the values (cheap
        discovery for subscribers that fetch lazily, e.g. log streaming)."""
        with self._cond:
            return {k: v[0] for (ch, k), v in self._state.items()
                    if ch == channel}


class Subscriber:
    """Client-side helper: blocking waits and background watch threads over a
    remote hub exposed via ``psub_poll`` / ``psub_poll_many`` RPCs."""

    def __init__(self, client):
        self._client = client  # RpcClient to the hub's host process

    def wait_for(self, channel: str, key: str, predicate,
                 timeout: Optional[float] = None,
                 last_version: int = 0):
        """Block until ``predicate(value)`` is true for a published value;
        returns (version, value). Raises TimeoutError."""
        deadline = None if timeout is None else time.monotonic() + timeout
        version = last_version
        while True:
            step = 30.0
            if deadline is not None:
                step = min(step, deadline - time.monotonic())
                if step <= 0:
                    raise TimeoutError(
                        f"pubsub wait on {channel}/{key} timed out")
            result = self._client.call("psub_poll", channel, key, version,
                                       step, timeout=step + 15.0)
            if result is None:
                continue
            version, value = result
            if predicate(value):
                return version, value

    def watch(self, channel: str, key: str, callback,
              stop_event: threading.Event,
              last_version: int = 0) -> threading.Thread:
        """Spawn a daemon thread invoking ``callback(version, value)`` on
        every update until ``stop_event`` is set."""

        def _dropped():
            from ray_tpu.core.config import config

            if config.core_metrics_enabled:
                from ray_tpu.core.coremetrics import PSUB_DROPPED_NOTIFIES

                PSUB_DROPPED_NOTIFIES.inc(1.0, {"channel": channel})

        def _loop():
            version = last_version
            while not stop_event.is_set():
                try:
                    result = self._client.call("psub_poll", channel, key,
                                               version, 10.0, timeout=25.0)
                except Exception:
                    _dropped()
                    if stop_event.wait(1.0):
                        return
                    continue
                if result is None:
                    continue
                version, value = result
                try:
                    callback(version, value)
                except Exception:
                    # The watch loop must outlive one bad callback, but
                    # a subscriber silently not applying updates is a
                    # routing/membership bug in the making.
                    from ray_tpu.util.ratelimit import log_every

                    _dropped()
                    log_every(f"pubsub.watch.{channel}", 10.0, logger,
                              "watch callback for %r failed", channel,
                              exc_info=True)

        thread = threading.Thread(target=_loop, daemon=True,
                                  name=f"psub-watch-{channel}-{key}")
        thread.start()
        return thread
