"""Process-wide typed configuration flags.

TPU-native analogue of the reference's ``RAY_CONFIG`` system
(reference: ``src/ray/common/ray_config_def.h:18-22`` — 216 typed flags, each
overridable via a ``RAY_<name>`` env var or ``ray.init(_system_config=...)``).
Here every flag is declared once in ``_FLAG_DEFS`` with a type and default;
``RAY_TPU_<NAME>`` env vars override at import time and
``init(_system_config={...})`` overrides at runtime.
"""

from __future__ import annotations

import os
from typing import Any, Dict

_FLAG_DEFS: Dict[str, tuple] = {
    # (type, default, doc)
    "inline_object_max_bytes": (int, 100 * 1024,
        "Task returns at or below this size are returned in-band to the owner's "
        "in-process store instead of the shared-memory store (reference: "
        "max_direct_call_object_size, ray_config_def.h)."),
    "object_store_memory_bytes": (int, 2 * 1024**3,
        "Default size of the per-node shared-memory object store segment."),
    "object_store_fallback_dir": (str, "/dev/shm",
        "Directory backing the shared-memory store files."),
    "worker_lease_timeout_s": (float, 30.0,
        "How long a task submission waits for a worker lease before erroring."),
    "worker_start_timeout_s": (float, 60.0,
        "How long the worker pool waits for a forked worker to register."),
    "worker_forkserver_enabled": (bool, True,
        "Fork default-env CPU workers from a pre-imported per-node template "
        "process (~10 ms) instead of spawning a fresh interpreter (~150 ms+) "
        "(reference: prestarted worker pool, worker_pool.h:357)."),
    "lease_undelivered_timeout_s": (float, 10.0,
        "A pooled worker that self-reports IDLE for this long while its "
        "lease is held had its grant reply or lease return lost on the "
        "network: the lease is credited back and the worker re-pooled. "
        "Dedicated (actor) forks whose actor runtime never started get "
        "3x this window before being killed (their creation was retried "
        "elsewhere). The lease GENERATION token keeps any straggler "
        "return/push from corrupting accounting. 0 disables."),
    "idle_worker_keep_s": (float, 300.0,
        "Idle workers beyond the soft pool limit are reaped after this long."),
    "heartbeat_period_s": (float, 1.0,
        "Node -> controller liveness heartbeat period (reference: raylet "
        "report period / GcsHealthCheckManager)."),
    "heartbeat_full_refresh_beats": (int, 10,
        "Delta heartbeats: unchanged availability ships as a liveness-only "
        "beat, with a full payload at least every this many beats "
        "(reference: RaySyncer versioned deltas, ray_syncer.h:88)."),
    "health_check_failure_threshold": (int, 60,
        "Missed heartbeats before the controller declares a node dead. "
        "Reference parity: ~60s of failed checks before death (period 3s "
        "x timeout 10s x threshold 5, ray_config_def.h:842-846). The old "
        "5s default proved trigger-happy — a 1000-actor surge starves "
        "heartbeat threads past it and a LIVE node's actors get reaped. "
        "Chaos tests that want fast detection override this."),
    "scheduler_spread_threshold": (float, 0.5,
        "Hybrid policy: prefer the local/first node until its utilization "
        "crosses this fraction, then spread (reference: "
        "scheduler_spread_threshold, hybrid_scheduling_policy.cc)."),
    "max_pending_lease_requests_per_key": (int, 10,
        "Max in-flight worker-lease requests per scheduling key (reference: "
        "ClusterSizeBasedLeaseRequestRateLimiter, core_worker.h:1963)."),
    "task_retry_delay_ms": (int, 100,
        "Delay before retrying a failed-but-retriable task."),
    "actor_restart_delay_ms": (int, 200,
        "Delay before restarting a dead actor with restarts remaining."),
    "get_poll_interval_s": (float, 0.01,
        "Polling interval for blocking get on remote objects."),
    "rpc_connect_retries": (int, 20,
        "TCP connect attempts (50ms apart) before an RPC endpoint is dead."),
    "rpc_outbound_cap_bytes": (int, 64 * 1024 * 1024,
        "Per-connection cap on bytes queued for send by an RpcServer's "
        "non-blocking write path. A peer that stops reading accumulates "
        "its replies here; past the cap the connection is dropped "
        "(backpressure — the reactor must never block on one slow peer)."),
    "log_to_driver": (bool, True,
        "Forward worker stdout/stderr lines to the driver process."),
    "dag_channels_enabled": (bool, True,
        "Upgrade same-host compiled-DAG edges to mutable shared-memory "
        "channels (reference: experimental_mutable_object_manager.h); "
        "disabled, every edge uses the RPC push path."),
    "dag_channel_capacity_bytes": (int, 8 * 1024 * 1024,
        "Per-slot size of one compiled-DAG channel edge; larger items "
        "fall back to the RPC push for that item."),
    "dag_channel_slots": (int, 3,
        "Ring depth of compiled-DAG channels (1-4): the writer may run "
        "this many items ahead of the reader's ack, overlapping stage "
        "compute with handoff (reference: buffered shared-memory "
        "channels, shared_memory_channel.py:169)."),
    "runtime_env_cache_bytes": (int, 2 * 1024**3,
        "Size budget for materialized runtime envs (/tmp/ray_tpu_envs): "
        "past it, least-recently-used env dirs not pinned by live workers "
        "are evicted (reference: the runtime-env agent's URI cache GC, "
        "runtime_env/plugin.py). 0 disables eviction."),
    "object_broadcast_min_bytes": (int, 8 * 1024 * 1024,
        "Objects at least this big use tree broadcast: the owner caps "
        "concurrent pulls per source and pullers re-register their copy "
        "as a new source (reference: push dedup, push_manager.h:30 — "
        "here generalized to a binomial distribution tree)."),
    "object_broadcast_fanout": (int, 0,
        "Max concurrent pulls served per source copy of a broadcast "
        "object; further pullers wait for a replica to come up. 0 "
        "(default) disables the tree: on a SINGLE host (incl. the "
        "multi-node-in-one-machine fixture) every source shares one "
        "CPU/NIC, so gating adds rounds without adding bandwidth — set "
        "to 2 on real multi-host clusters where each replica node "
        "contributes its own NIC."),
    "object_pull_slot_lease_s": (float, 300.0,
        "A broadcast pull slot auto-expires after this long (crashed "
        "puller must not wedge the object's distribution tree)."),
    "event_buffer_max": (int, 10000,
        "Max buffered task state-transition events per worker (reference: "
        "TaskEventBuffer, task_event_buffer.h:206)."),
    "object_transfer_chunk_bytes": (int, 8 * 1024 * 1024,
        "Node-to-node object pulls move in chunks of this size (reference: "
        "object_manager_default_chunk_size, object_manager.h:117)."),
    "max_pull_bytes_in_flight": (int, 256 * 1024 * 1024,
        "Admission control: per-process cap on chunk bytes concurrently in "
        "flight for remote object pulls (reference: PullManager's "
        "num_bytes_available budget, pull_manager.h:52)."),
    "object_spill_dir": (str, "/tmp/ray_tpu_spill",
        "Directory for objects spilled to disk when the shared-memory store "
        "is full (reference: local_object_manager.h:110 spill-to-fs)."),
    "ref_counting_enabled": (bool, True,
        "Automatic object lifetimes: ObjectRef handles are tracked per "
        "process and reported to owners; objects free when the cluster-wide "
        "handle count drops to zero (reference: reference_count.h:61)."),
    "ref_free_grace_s": (float, 2.0,
        "An owner frees a zero-refcount object only after it has stayed at "
        "zero this long (absorbs in-flight handle registrations)."),
    "ref_flush_interval_s": (float, 0.2,
        "Batched ref-count updates flush to owners at this period."),
    "max_lineage_entries": (int, 10000,
        "Owner-kept task lineage entries for object reconstruction "
        "(reference: max_lineage_bytes, task_manager.h:215)."),
    "reconstruction_max_attempts": (int, 3,
        "How many times a lost object's producing task is re-executed "
        "(reference: object_recovery_manager.h:41)."),
    "accel_env_vars": (str, "PALLAS_AXON_POOL_IPS",
        "Comma-separated env vars stripped from CPU-only workers at fork: "
        "site hooks keyed on these attach accelerators (and import jax) "
        "into every python process, a startup tax pure-CPU task workers "
        "skip. Leases holding a TPU resource keep them."),
    "worker_log_dir": (str, f"/tmp/ray_tpu_logs_{os.getuid()}",
        "Per-node worker stdout/stderr log files live under "
        "<dir>/<node_hex>/ (reference: session_latest/logs); per-uid "
        "default so multi-user hosts don't collide."),
    "log_monitor_scan_s": (float, 0.5,
        "Log monitor tail period (reference: log_monitor.py scan loop)."),
    "log_rotation_max_bytes": (int, 64 * 1024 * 1024,
        "A worker log file past this size is truncated after its tail is "
        "consumed (reference: log_rotation_max_bytes)."),
    "log_window_lines": (int, 500,
        "Published log window size per node; drivers diff end counters so "
        "bursts up to this size are never lost between polls."),
    "memory_usage_threshold": (float, 0.95,
        "Node memory fraction above which the memory monitor starts killing "
        "workers (reference: memory_usage_threshold, ray_config_def.h:65)."),
    "memory_monitor_refresh_s": (float, 1.0,
        "Memory monitor check period; 0 disables the monitor (reference: "
        "memory_monitor_refresh_ms)."),
    "memory_kill_interval_s": (float, 2.0,
        "Minimum spacing between memory-monitor worker kills (reference: "
        "memory_monitor_min_wait_between_kills)."),
    "worker_killing_policy": (str, "retriable_fifo",
        "OOM victim selection: 'retriable_fifo' (newest retriable task "
        "first) or 'group_by_owner' (largest owner's newest task first) "
        "(reference: worker_killing_policy*.cc)."),
    "lease_spillback_queue_depth": (int, 32,
        "A node whose lease queue is deeper than this immediately rejects "
        "new general-pool leases ('spillback') so submitters re-pick "
        "another node from the controller's fresher view instead of "
        "queueing behind a stale choice (reference: hybrid policy "
        "spillback redirects, hybrid_scheduling_policy.cc). 0 disables."),
    "client_session_timeout_s": (float, 60.0,
        "Thin-client sessions with no RPC (incl. keepalive pings) for this "
        "long are reaped server-side — their refs released and unnamed "
        "actors killed, as if the client driver exited (reference: Ray "
        "Client proxied-driver lifetime)."),
    "dead_actor_cache_count": (int, 1000,
        "Dead actor records (and their pubsub entries) retained for late "
        "callers before being reaped (reference: "
        "maximum_gcs_destroyed_actor_cached_count, ray_config_def.h)."),
    "prefix_pool_entries": (int, 8,
        "Entries in a DecodeEngine's device-resident prefix KV pool "
        "(serve/prefix_cache.py): cached prompt prefixes spliced into a "
        "request's slot at admission so only the uncached suffix is "
        "prefilled (vLLM/SGLang-style prefix caching on static buckets). "
        "Each entry costs 2 * L * C_prefix * KV * D cache bytes. "
        "0 disables the prefix cache."),
    "prefix_match_min_tokens": (int, 16,
        "Minimum shared-prefix length (tokens) for a prefix-cache hit; "
        "prompts shorter than this are neither matched nor inserted "
        "(splicing a tiny prefix costs more dispatch than it saves)."),
    "serve_request_timeout_s": (float, 70.0,
        "Default end-to-end deadline for a serve request when the client "
        "sets none (HTTP header X-Request-Timeout-S or "
        "DeploymentHandle.options(timeout_s=...) override per request). "
        "Propagated proxy -> handle -> replica -> DecodeEngine, which "
        "finishes the slot with DeadlineExceededError instead of decoding "
        "for a caller that already gave up. 0 disables the default (no "
        "deadline unless the client sends one)."),
    "decode_queue_max": (int, 0,
        "Cap on a DecodeEngine's pending (unadmitted) request queue. Past "
        "it, submit() sheds the request immediately with OverloadedError "
        "(mapped to HTTP 503 + Retry-After) instead of queueing it into "
        "minutes of latency. 0 = slots * 8."),
    "handle_retry_budget": (int, 3,
        "Per-request attempts a DeploymentHandle router makes when a "
        "replica dies mid-call (ActorDiedError/ActorUnavailableError). "
        "Streaming requests never retry after the first item, and no "
        "retry is attempted past the request deadline."),
    "handle_retry_backoff_ms": (int, 50,
        "Base backoff before a handle retry; doubles each attempt with "
        "+/-50% jitter so a replica death under load heals instead of "
        "amplifying into a synchronized retry storm on the survivors."),
    "kv_page_tokens": (int, 0,
        "Page size (tokens) of a DecodeEngine's paged KV pool. >0 switches "
        "the engine from per-slot monolithic cache rows to a shared device "
        "pool of fixed-size pages addressed through per-slot block tables "
        "(vLLM-style paged attention on static shapes): slots consume only "
        "the pages their sequence actually covers, prefix sharing splices "
        "block-table entries with zero device copies, and eviction frees "
        "page-granular tail segments. Must divide the engine capacity. "
        "0 = contiguous whole-row cache (pre-paging behavior)."),
    "kv_pool_pages": (int, 0,
        "Pages in a paged DecodeEngine's device KV pool. The pool may be "
        "OVERCOMMITTED (pages < slots * capacity / kv_page_tokens): more "
        "concurrent sequences fit the same HBM bytes, and when the pool "
        "truly runs dry the engine reclaims prefix-cache pins first and "
        "then preempts the youngest request (recompute-style requeue). "
        "0 = slots * capacity / kv_page_tokens (no overcommit)."),
    "kv_prefix_max_pages": (int, 0,
        "Cap on pool pages pinned by the paged prefix index (cached "
        "prompt prefixes kept resident after their request completes). "
        "Past it, least-recently-used tail pages unpin first. "
        "0 = kv_pool_pages // 4."),
    "prefill_chunk_tokens": (int, 0,
        "Chunked-prefill interleaving for paged DecodeEngines: prompt "
        "prefills longer than this run as a sequence of at most one "
        "chunk-sized prefill program per decode step, scheduled between "
        "decode steps — a long admission can stall active streams for at "
        "most ONE chunk instead of its whole prefill. 0 disables "
        "(monolithic prefill at admission, pre-chunking behavior)."),
    "spec_k": (int, 0,
        "Speculative-decoding depth for DecodeEngines given a draft "
        "model: a small draft model proposes k tokens per active slot "
        "per step and the target model verifies all k+1 positions in "
        "ONE batched forward (the paged ragged-position gather), so a "
        "step emits 1..k+1 tokens per slot. Greedy output is "
        "bit-identical to non-speculative decode (longest-matching-"
        "prefix acceptance); sampled (temperature > 0) requests fall "
        "back to per-token decode. Requires paged KV (kv_page_tokens "
        "> 0). 0 disables (pre-spec behavior, byte-identical)."),
    "spec_draft_model": (str, "",
        "Draft-model preset name (models/llama.PRESETS) for "
        "LlamaDecodeDeployment's speculative mode — a model a few times "
        "smaller than the target preset. Empty disables spec mode at "
        "the deployment level; engines constructed directly take draft "
        "params/config explicitly."),
    "spec_draft_pool_pages": (int, 0,
        "Pages in the draft model's OWN paged KV pool (spec mode). The "
        "draft tracks the same sequence positions as the target but at "
        "draft-model width, so its pool is the same page count at a "
        "fraction of the bytes. Size it >= kv_pool_pages or draft-pool "
        "pressure preempts requests the target pool could still seat. "
        "0 = match kv_pool_pages."),
    "decode_device_sampler": (bool, False,
        "Fold sampling into the decode program (device-side argmax / "
        "per-row categorical under out_shardings) so each step returns "
        "token ids instead of round-tripping (slots, vocab) logits to "
        "the host sampler. Greedy rows are bit-identical to the host "
        "sampler; temperature > 0 rows draw from the device RNG stream "
        "(a DIFFERENT stream than the host sampler's numpy generator), "
        "which is why this is opt-in. Requests needing host-side logit "
        "processing keep the host path regardless."),
    "decode_warmup": (bool, False,
        "Pre-dispatch a DecodeEngine's steady-state program set (decode, "
        "decode-chunk grid, spec draft/verify, device sampler) at "
        "deployment construction so jit compiles land before traffic "
        "instead of under the first requests' latency. The steplog's "
        "jit-compile events then show only prefill buckets (which stay "
        "lazy — their grid depends on the live prompt mix)."),
    "decode_mesh_shape": (str, "",
        "Default (batch, model) decode mesh for DecodeEngines that are "
        "not given an explicit mesh_shape, e.g. '2x4': the engine spans "
        "that many devices with GSPMD-sharded weights/KV (NamedSharding "
        "over a named 2-D mesh; sharded logits are bit-exact vs the "
        "single-chip path). Empty = single-chip engines (pre-mesh "
        "behavior). Deployment-level mesh_shape overrides per app."),
    "slice_affinity_enabled": (bool, True,
        "Serve routers prefer replicas on the caller's own pod slice "
        "(ICI-local) over cross-slice replicas when both can take the "
        "request; load still wins past saturation. No-op when nodes "
        "advertise no slice topology."),
    "prefix_affinity_enabled": (bool, True,
        "Serve routers hash a request's leading token buckets and prefer "
        "the replica advertising that prefix in its cache (falling back "
        "to pow-2 least-loaded), so hot system prompts stay resident on "
        "one replica's prefix pool instead of re-prefilling on every "
        "replica."),
    "serve_metrics_enabled": (bool, True,
        "Serve SLO instruments (serve/metrics.py): TTFT, inter-token and "
        "queue-wait histograms plus request-outcome/retry/preemption "
        "counters, labeled by deployment, flushed through the cluster "
        "metrics pipeline and served as Prometheus text from the HTTP "
        "proxy's /metrics route. All observations are per-REQUEST (never "
        "per token), so the decode step loop pays nothing per step."),
    "serve_trace_spans": (bool, True,
        "Request tracing through the serve plane: the HTTP proxy, router "
        "and DecodeEngine record spans (admission/queue wait, prefill "
        "chunks, decode, retries, preemption, outcome) into the task-event "
        "buffer so `python -m ray_tpu timeline --serve` renders one "
        "causally-linked Chrome trace across processes. Spans are "
        "per-request/per-chunk, never per token or per step."),
    "decode_step_timeline": (int, 256,
        "Entries in a DecodeEngine's step-timeline ring "
        "(serve/steplog.py): per-step phase (prefill chunk vs decode), "
        "batch occupancy and page alloc/free/preempt + jit-compile "
        "events, dumpable via engine stats / the replica RPC and merged "
        "into the serve Chrome trace. 0 disables the recorder."),
    "metrics_flush_interval_s": (float, 5.0,
        "Period of the per-process metrics flusher pushing registry "
        "snapshots to the cluster controller. Snapshots are CUMULATIVE, "
        "so a missed push (controller restart) never double-counts — the "
        "next successful push supersedes it."),
    "core_metrics_enabled": (bool, True,
        "Core-plane instrumentation (core/coremetrics.py): RPC write-path "
        "and dial counters, object put/get/transfer instruments, pubsub "
        "deliver latency + subscriber lag, controller scheduling/heartbeat "
        "instruments. Hot paths pay plain attribute increments only; the "
        "registry is touched at snapshot time by collectors. Off = the "
        "pre-instrumentation fast path (bench_obs.py measures the delta)."),
    "metrics_max_series": (int, 2000,
        "Per-process cap on metric series included in one registry "
        "snapshot push. Past it, overflow series are dropped from the "
        "push (insertion order keeps established series flowing) and a "
        "metrics_series_dropped gauge reports the overflow — a runaway "
        "label-cardinality producer degrades visibly instead of growing "
        "every heartbeat-cadence RPC without bound."),
    "faultinject_path": (str, "",
        "Path of a JSON fault-rules file activating util/faultinject.py "
        "injection points (kill-process, drop/delay/error a named RPC "
        "endpoint, pause heartbeats, partition a peer). Empty (default) "
        "disables every injection point at the cost of one attribute "
        "read. Set via RAY_TPU_FAULTINJECT_PATH before ray_tpu.init so "
        "worker processes inherit it; chaos tests drive faults by "
        "editing the file (re-read on mtime change)."),
    "mh_member_beat_period_s": (float, 0.25,
        "Period of a host-group member's membership heartbeat to the "
        "group registry (core/multihost.py). The beat carries the "
        "member's group epoch; a 'fenced' reply is how a zombie member "
        "of a deposed gang incarnation learns to stop touching group "
        "state."),
    "ctrl_call_timeout_s": (float, 30.0,
        "Transport bound on one-shot control-plane RPCs (gang registry "
        "reads/writes, lease release, taints, serve controller state "
        "saves, autopilot actions). The client treats timeout=None as "
        "park-forever, so every such call carries this instead: a "
        "dropped reply becomes a typed TimeoutError the caller's "
        "retry/refusal logic handles, never a silent distributed hang "
        "(graftlint rpc-call-no-timeout). Long-polls (barriers, pubsub "
        "watches) are NOT governed by this — they carry their own "
        "window-derived bounds."),
    "mh_monitor_period_s": (float, 0.3,
        "Period of the HostGroup driver-side monitor pinging every gang "
        "member. One failed member reconciles the WHOLE group (kill all, "
        "release the sub-slice exactly once, optional restart under a "
        "bumped epoch)."),
    "mh_ping_timeout_s": (float, 5.0,
        "Timeout on each monitor ping before a gang member is declared "
        "dead (the push to a SIGKILLed worker fails fast; this bounds "
        "the wedged-but-listening case)."),
    "mh_barrier_timeout_s": (float, 30.0,
        "Default timeout for group rendezvous barriers (program-hash "
        "checks, jax bootstrap alignment). A timeout is a typed refusal "
        "naming the absent members — never a silent hang."),
    "mh_form_timeout_s": (float, 60.0,
        "How long gang formation waits for every member actor to come "
        "up before declaring the spawn failed (all-or-nothing: a "
        "partial gang is torn down and the sub-slice released)."),
    "rpc_reconnect_backoff_base_ms": (int, 50,
        "First-retry pause of a ReconnectingClient after a transport "
        "failure. Doubles per consecutive failure (with +/-50% jitter) "
        "up to rpc_reconnect_backoff_cap_ms — the first retry stays "
        "fast (a controller blip heals in ~one beat) while a DEAD "
        "controller costs a capped trickle of dials instead of the "
        "tight 0.2 s loop ray_tpu doctor flags as a reconnect storm."),
    "rpc_reconnect_backoff_cap_ms": (int, 2000,
        "Ceiling on the ReconnectingClient retry backoff. Bounds the "
        "extra latency a client adds on top of controller recovery: "
        "after the controller returns, the next retry lands within at "
        "most this long (x1.5 jitter)."),
    "pipe_step_timeout_s": (float, 120.0,
        "Wall-clock bound on one pipeline-parallel optimizer step "
        "(train/pipeline_plane.py): past it the driver raises a typed "
        "PipelineError naming the per-stage schedule state instead of "
        "hanging — a wedged stage becomes a diagnosis, not a stall "
        "(see ray_tpu doctor's pipeline-stall signature)."),
    "pipe_setup_timeout_s": (float, 120.0,
        "How long PipelinePlane waits for every stage actor to pull "
        "its params/optimizer state and compile its programs during "
        "(re)formation before declaring the setup failed."),
    "pipe_snapshot_every": (int, 1,
        "PipelinePlane pulls a driver-owned snapshot of every stage's "
        "params/optimizer state every N completed optimizer steps — "
        "the resume point after a whole-gang restart (a snapshot owned "
        "by a stage actor would die with it). 0 disables snapshots "
        "(a gang death then restarts training from step 0)."),
    "pipe_trace_spans": (bool, True,
        "Train-plane tracing (train/pipeline_plane.py): the pipeline "
        "driver opens one root span per optimizer step and every stage "
        "actor records fwd/bwd/apply spans with {step, mb, stage} attrs "
        "into the task-event buffer, so `python -m ray_tpu timeline "
        "--train` renders per-stage process rows whose gaps ARE the "
        "1F1B bubble. Spans are per stage-RPC, never per tensor "
        "element; stage-side emission is additionally gated on an "
        "active trace context, so an untraced step pays one contextvar "
        "read per call."),
    "pipe_trace_sample_every": (int, 4,
        "Head-sampling period of the train-plane tracer: every Nth "
        "optimizer step opens the pipe:step root span (stage/cell "
        "spans follow the propagated context, so a sampled step is "
        "traced END TO END and an unsampled one records nothing). A "
        "fully-traced 1F1B step emits ~180 span events (per-cell "
        "driver+stage spans, object put/get, actor exec) — ~5% of a "
        "200 ms debug step on the CPU box — so sampling keeps the "
        "always-on cost under the 2% bar while every timeline still "
        "shows complete representative steps. 1 traces every step."),
    "flightrec_enabled": (bool, True,
        "Cluster flight recorder (util/flightrec.py): a bounded "
        "per-process ring of structured control-plane events (gang "
        "epochs/reconciles, barrier entries, pipeline stage clocks, "
        "snapshot push/pull, faultinject fires, actor death causes) "
        "persisted for `ray_tpu doctor --post-mortem`. Off = every "
        "record() is one attribute read."),
    "flightrec_ring": (int, 512,
        "Events kept per process by the flight recorder (deque maxlen; "
        "oldest evicted first). The ring records control-plane facts, "
        "not data-plane traffic — 512 covers minutes of gang/pipeline "
        "lifecycle at production cadences."),
    "flightrec_dir": (str, f"/tmp/ray_tpu_flightrec_{os.getuid()}",
        "Per-HOST directory the flight recorder persists per-process "
        "rings into (fr-<pid>.json, atomic replace). fr_dump / doctor "
        "--post-mortem merge every file here; on multi-host rigs "
        "collect each host's dir. Per-uid default so shared dev hosts "
        "don't collide."),
    "flightrec_flush_s": (float, 0.5,
        "Period of the flight recorder's background flush to "
        "flightrec_dir while events keep arriving. A SIGKILL keeps "
        "everything up to the last flush (faultinject die rules flush "
        "synchronously first, so injected crashes are fully recorded)."),
    "pipe_peak_tflops": (float, 0.0,
        "Aggregate peak TFLOP/s of a training gang, for the pipeline "
        "plane's MFU estimate gauge (pipeline_mfu_pct = achieved model "
        "TFLOP/s / peak x 100; achieved is always exported as "
        "pipeline_model_tflops). 0 (default) disables the MFU gauge — "
        "there is no honest peak number for a time-sliced CPU host; "
        "set it to chips x per-chip peak on a real rig."),
    "serve_adopt_timeout_s": (float, 5.0,
        "How long a restarted serve controller pings the replica/proxy "
        "handles from its checkpoint before declaring the stragglers "
        "dead. Alive replicas are ADOPTED (same actor, same sub-slice "
        "reservation — no respawn, no cold prefill); dead ones are "
        "replaced and their reservations queued for release. Bounds "
        "control-plane MTTR: snapshots republish right after this "
        "window at the latest."),
    "serve_handoff_ttl_s": (float, 60.0,
        "How long a prefill replica's handoff ledger keeps a published "
        "KV-page handoff (object-plane refs + descriptor) that nobody "
        "discharged. The router discharges on adopt-ack or abort; this "
        "TTL only catches a router that died mid-splice — the sweep "
        "(driven by the controller's reconcile stats pull) frees the "
        "expired refs so an orphaned handoff can never pin its page "
        "payload past the window. Must exceed the worst-case publish->"
        "adopt gap (seconds); expiry after a successful adopt is "
        "harmless (the decode replica already fetched the bytes)."),
    "serve_mttr_bound_s": (float, 30.0,
        "Acceptance bound on serve control-plane MTTR: controller "
        "death -> routing snapshots flowing again (epoch-bumped "
        "republish observed by routers). The chaos suite and "
        "bench_chaos.py assert/record against this; it is a TEST bound, "
        "not a runtime knob — nothing throttles recovery to it."),
    "controller_metrics_http_port": (int, -1,
        "Port for the controller-side Prometheus /metrics HTTP endpoint "
        "(whole-cluster exposition text, series labeled by node/role/pid). "
        "-1 disables; 0 binds an ephemeral port (Controller."
        "metrics_http_addr reports it). The dashboard serves the same "
        "text at its own /metrics route."),
    "autopilot_enabled": (bool, False,
        "Global kill switch for closed-loop remediation (autopilot.py). "
        "OFF (default) = the reconciler observes and records what it "
        "WOULD do but takes no action — byte-identical legacy behavior. "
        "ON = doctor signatures that persist across the hysteresis "
        "window become fenced, rate-limited control actions (taint host, "
        "reschedule gang, shed tenant, resize deployment)."),
    "autopilot_dry_run": (bool, False,
        "Autopilot evaluates the full pipeline (hysteresis, rate "
        "limits, fencing) and writes audit records with outcome "
        "'dry-run', but never mutates the cluster. Subordinate to "
        "autopilot_enabled: with the kill switch OFF nothing runs at "
        "all; with it ON, dry-run is the safe observe-only mode the "
        "CLI's --dry-run uses."),
    "autopilot_poll_s": (float, 5.0,
        "Autopilot reconcile cadence: each tick collects a doctor "
        "window (two metrics snapshots interval_s apart is the "
        "caller's job — the loop just spaces ticks) and steps the "
        "remediation pipeline. Also the denominator of 'windows' in "
        "autopilot_hysteresis_windows."),
    "autopilot_hysteresis_windows": (int, 2,
        "Consecutive doctor windows a (signature, source) pair must "
        "persist before autopilot may act on it. 2 (default) means a "
        "one-window transient — a single slow heartbeat, one queue "
        "spike — NEVER triggers remediation. 1 disables hysteresis "
        "(test/bench use)."),
    "autopilot_rate_per_min": (float, 2.0,
        "Token-bucket refill rate, actions per minute PER ACTION CLASS "
        "(taint-host, reschedule-gang, shed-tenant, resize-deployment "
        "each get their own bucket). Actions past the budget are "
        "suppressed (autopilot_suppressed_total{reason='rate-limit'}) "
        "and retried on a later tick if the signature persists."),
    "autopilot_burst": (int, 2,
        "Token-bucket capacity per action class: how many actions of "
        "one class may fire back-to-back before the per-minute refill "
        "gates further ones. Bounds blast radius when a correlated "
        "fault (rack loss) lights up many signatures at once."),
    "autopilot_taint_ttl_s": (float, 120.0,
        "How long a taint-host demotion keeps a node out of new "
        "gang/replica placement. After the TTL the taint lapses and "
        "the host is re-admitted IF its recent heartbeats look healthy "
        "(probe-based re-admission: the controller checks the node's "
        "last-heartbeat freshness before lifting the taint; a host "
        "still wedged keeps its taint another TTL)."),
}


class _Config:
    def __init__(self):
        self._values: Dict[str, Any] = {}
        for name, (typ, default, _doc) in _FLAG_DEFS.items():
            env = os.environ.get(f"RAY_TPU_{name.upper()}")
            if env is not None:
                if typ is bool:
                    self._values[name] = env.lower() in ("1", "true", "yes")
                else:
                    self._values[name] = typ(env)
            else:
                self._values[name] = default

    def __getattr__(self, name: str):
        try:
            return self.__dict__["_values"][name]
        except KeyError:
            raise AttributeError(name) from None

    def __setattr__(self, name: str, value) -> None:
        """Direct assignment writes the flag store. Without this, a
        ``config.flag = x`` would create an instance attribute that
        permanently SHADOWS the store — later ``update()`` calls would
        write values no reader ever sees (a real cross-test corruption)."""
        if name.startswith("_"):
            super().__setattr__(name, value)
            return
        values = self.__dict__.get("_values")
        if values is None or name not in values:
            raise AttributeError(f"unknown config flag {name!r}")
        typ = _FLAG_DEFS[name][0]
        if typ is bool and isinstance(value, str):
            value = value.lower() in ("1", "true", "yes")
        values[name] = typ(value)

    def update(self, overrides: Dict[str, Any]) -> None:
        """Apply ``_system_config`` style overrides (validated by name/type)."""
        for name, value in overrides.items():
            if name not in _FLAG_DEFS:
                raise ValueError(f"Unknown config flag: {name}")
            typ = _FLAG_DEFS[name][0]
            self._values[name] = typ(value)

    def snapshot(self) -> Dict[str, Any]:
        return dict(self._values)


config = _Config()
