"""CoreWorker: the per-process runtime embedded in every driver and worker.

Analogue of the reference's core worker (``src/ray/core_worker/core_worker.h:295``)
— the single most load-bearing component. Every process (driver or worker)
embeds one: it owns the in-process object store, serves owned objects to
borrowers, submits tasks (normal + actor) with owner-side dependency
resolution, and executes pushed tasks.

Key protocol decisions mirrored from the reference:

* **Ownership** — the submitting process owns task returns and ``put``
  objects; return values flow back to the owner and are served from its
  store (``task_manager.h:208``, ``memory_store.h:43``).
* **Lease-based direct transport** — the submitter resolves dependencies
  *first* (``dependency_resolver.h`` — this ordering is what prevents the
  classic hold-a-worker-while-waiting-for-deps deadlock), then asks the
  cluster scheduler for a node, leases a worker from that node's pool, and
  pushes the task spec directly owner->worker
  (``direct_task_transport.h:75``).
* **Ordered actor calls** — per-caller sequence numbers; the actor executes
  calls from each caller in submission order unless ``max_concurrency > 1``
  or the actor is async (``direct_actor_task_submitter.h:74``,
  ``ActorSchedulingQueue``).
* **Task retries** — owner-side retry on worker crash
  (``task_manager.h:269`` RetryTaskIfPossible).
"""

from __future__ import annotations

import hashlib
import heapq
import logging
import os
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu.core import serialization
from ray_tpu.core.config import config
from ray_tpu.util import tracing
from ray_tpu.util.ratelimit import log_every

logger = logging.getLogger(__name__)
from ray_tpu.core.errors import (
    ActorDiedError,
    ObjectLostError,
    OutOfMemoryError,
    RayTpuError,
    TaskError,
    WorkerCrashedError,
)
from ray_tpu.core.ids import ActorID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.object_store import MemoryStore, wait_any
from ray_tpu.core.rpc import (
    ClientPool,
    ReconnectingClient,
    RemoteCallError,
    RpcError,
    RpcServer,
)

Addr = Tuple[str, int]

_core_worker: Optional["CoreWorker"] = None
_core_worker_lock = threading.Lock()

# How long an actor's ordered queue waits for a missing sequence number
# before treating it as skipped (see ActorExecutionRuntime._run_ordered).
_GAP_WAIT_S = 30.0


def _dump_stacks() -> str:
    from ray_tpu.util.tracing import dump_stacks

    return dump_stacks()


def _profile_cpu(duration_s: float = 3.0, hz: float = 100.0):
    from ray_tpu.util.profiling import sample_stacks

    return sample_stacks(duration_s, hz)


def _profile_heap(top_n: int = 25):
    from ray_tpu.util.profiling import heap_profile

    return heap_profile(top_n)


def _profile_heap_stop():
    from ray_tpu.util.profiling import stop_heap_profile

    return stop_heap_profile()


def get_core_worker() -> "CoreWorker":
    if _core_worker is None:
        raise RayTpuError(
            "ray_tpu has not been initialized; call ray_tpu.init() first.")
    return _core_worker


def set_core_worker(worker: Optional["CoreWorker"]) -> None:
    global _core_worker
    with _core_worker_lock:
        _core_worker = worker


def is_initialized() -> bool:
    return _core_worker is not None


class CoreWorker:
    def __init__(
        self,
        mode: str,  # "driver" | "worker"
        controller_addr: Addr,
        node_addr: Addr,
        node_id: NodeID,
        worker_id: Optional[WorkerID] = None,
    ):
        self.mode = mode
        self.worker_id = worker_id or WorkerID.from_random()
        self.node_id = node_id
        self.node_addr = tuple(node_addr)
        self.controller_addr = tuple(controller_addr)

        self.store = MemoryStore()
        self.clients = ClientPool()
        # Controller link retries through reconnects, so a head restart
        # (controller FT) stalls control-plane calls briefly instead of
        # failing in-flight tasks (reference: gcs_rpc_client.h retries).
        self.controller = ReconnectingClient(tuple(controller_addr))
        # Lazily opened shared-memory stores: our own node's (for writes) and
        # any local store we read from. {path: ShmStore}
        self._shm_stores: Dict[str, Any] = {}
        self._shm_lock = threading.Lock()
        self._fn_cache: Dict[str, Callable] = {}
        self._fn_cache_lock = threading.Lock()
        self._actor_runtime: Optional[ActorExecutionRuntime] = None
        self._current_task_desc = threading.local()
        self._shutdown = threading.Event()
        # Work counters, reported in worker_ping so the node can reclaim
        # leases whose grant or return was lost on the network (the
        # worker would otherwise sit leased forever).
        self.tasks_received = 0
        self.active_tasks = 0

        # Owner-kept task lineage for object reconstruction: return oid ->
        # shared record of the producing task (reference: task_manager.h:215
        # lineage, object_recovery_manager.h:41).
        self._lineage: Dict[ObjectID, Dict[str, Any]] = {}
        self._lineage_lock = threading.Lock()
        # Streaming-generator returns: task id bytes -> stream state
        # (items: ObjectRefs in yield order; total set when the task ends).
        self._streams: Dict[bytes, Dict[str, Any]] = {}
        self._streams_cond = threading.Condition()
        # Admission control for remote object pulls (reference: PullManager's
        # memory budget, pull_manager.h:52): bounded chunk slots.
        slots = max(1, config.max_pull_bytes_in_flight
                    // config.object_transfer_chunk_bytes)
        self._pull_slots = threading.BoundedSemaphore(slots)
        # Owner-side broadcast trees (reference: push_manager.h:30 push
        # dedup, generalized): per big object, the set of replica
        # locations and the leased pull slots per source.
        self._bcast: Dict[bytes, Dict[str, Any]] = {}
        self._bcast_cond = threading.Condition()

        self.server = RpcServer(
            handlers={
                "get_object": self._handle_get_object,
                "wait_object": self._handle_wait_object,
                "peek_object": self._handle_peek_object,
                # remote-free entry point for external tooling (the
                # owner frees its own objects via free_object directly)
                "free_object": self._handle_free_object,
                "pull_done": self._handle_pull_done,
                "pull_failed": self._handle_pull_failed,
                "ref_update": self._handle_ref_update,
                "reconstruct_object": self._handle_reconstruct,
                "push_task": self._handle_push_task,
                "push_task_batch": self._handle_push_task_batch,
                "stream_item": self._handle_stream_item,
                "start_actor": self._handle_start_actor,
                "push_actor_task": self._handle_push_actor_task,
                # graceful-stop hook (nodes SIGTERM workers today);
                # reserved for drain-before-kill
                # graftlint: disable=rpc-dead-endpoint
                "shutdown_worker": self._handle_shutdown,
                "dump_stacks": _dump_stacks,
                # On-demand profiling (reference: profile_manager.py:79
                # py-spy CPU + :190 memray heap — native equivalents).
                "profile_cpu": _profile_cpu,
                "profile_heap": _profile_heap,
                "profile_heap_stop": _profile_heap_stop,
                "ping": lambda: "pong",
            },
            name=f"{mode}-core",
            max_workers=128,
            inline_methods={"peek_object", "free_object", "ref_update",
                            # Broadcast slot releases must make progress
                            # while the pool is saturated with blocked
                            # get_object long-polls — else each tree round
                            # stalls a full long-poll window. Replies are
                            # queued (never sent blocking) by the reactor
                            # write path, so inlining is safe even when a
                            # peer reads slowly; ping rides inline so
                            # liveness probes skip the pool hop entirely.
                            "pull_done", "pull_failed", "ping"},
        )
        self.addr: Addr = self.server.addr
        self.submitter = TaskSubmitter(self)
        # Owner-side task state-transition buffer (reference:
        # TaskEventBuffer, task_event_buffer.h:206): flushed to the
        # controller by the sweeper thread, bounded by event_buffer_max.
        self._task_events: List[Dict[str, Any]] = []
        self._task_events_lock = threading.Lock()
        from ray_tpu.util import metrics as um

        um.add_collector(self._collect_core_metrics)
        self._sweeper = threading.Thread(
            target=self._sweep_loop, name="ref-sweeper", daemon=True)
        self._sweeper.start()

    def _collect_core_metrics(self) -> None:
        """Snapshot-time store gauges (weakly registered — dies with the
        core worker)."""
        if not config.core_metrics_enabled:
            return
        from ray_tpu.core import coremetrics as cmx

        cmx.OBJ_STORE_ENTRIES.set(float(self.store.size()))
        cmx.OBJ_STORE_BYTES.set(float(self.store.data_bytes()))

    # -------------------------------------------------- shared-memory store

    def _open_shm(self, path: str):
        with self._shm_lock:
            store = self._shm_stores.get(path)
            if store is None:
                from ray_tpu._native.objstore import ShmStore

                # One-time per-path init (may compile the native .so on
                # first use). Serializing it is the point: two threads
                # must not mmap/build the same store concurrently, and
                # after the first call it's a dict hit.
                # graftlint: disable=lock-held-blocking
                store = ShmStore(path)
                self._shm_stores[path] = store
            return store

    def _shm_locator(self, oid: ObjectID) -> Dict[str, Any]:
        from ray_tpu.core.node import shm_store_path

        return {
            "path": shm_store_path(self.node_id),
            "node_id": self.node_id.binary(),
            "node_addr": self.node_addr,
            "oid": oid.binary(),
        }

    def _try_put_frame(self, oid: ObjectID, total: int,
                       write) -> Optional[Dict]:
        """Reserve ``total`` bytes in this node's store and let ``write``
        fill them in place (single copy: pickle buffers -> shm mmap); falls
        back to the node's spill directory when the store can't fit it
        (reference: local_object_manager.h:110 spill-to-fs — spilling
        happens at write time because pinned primary copies are not
        evictable). Returns the locator, or None only when both fail."""
        try:
            from ray_tpu.core.node import shm_store_path

            store = self._open_shm(shm_store_path(self.node_id))
            buf = store.create_buffer(oid.binary(), total)
            if buf is not None:
                write(buf)
                # Owner holds the primary-copy pin until free: without it,
                # LRU eviction under pressure could drop the only copy of a
                # live object (ObjectLostError on a later get).
                self.store._entry(oid).shm_pin = store.seal(
                    oid.binary(), pin=True)
                loc = self._shm_locator(oid)
                loc["total"] = total  # lets the owner pick broadcast mode
                return loc
        except OSError:
            pass
        return self._try_spill(oid, total, write)

    def _try_spill(self, oid: ObjectID, total: int, write) -> Optional[Dict]:
        """Write the frame into a file in this node's spill dir (mmap-backed,
        same single-copy discipline) and return a locator the node's object
        server can resolve (read_shm_* check the spill dir)."""
        import mmap as _mmap

        try:
            from ray_tpu.core.node import spill_dir, spill_file

            os.makedirs(spill_dir(self.node_id), exist_ok=True)
            path = spill_file(self.node_id, oid.binary())
            tmp = path + ".tmp"
            with open(tmp, "wb+") as f:
                if total:
                    # Allocate blocks up front: ENOSPC surfaces here as
                    # OSError (caught below) instead of a SIGBUS when the
                    # mmap write faults on a sparse hole.
                    os.posix_fallocate(f.fileno(), 0, total)
                    with _mmap.mmap(f.fileno(), total) as m:
                        write(memoryview(m))
            os.rename(tmp, path)
            loc = self._shm_locator(oid)
            loc["spill"] = path
            loc["total"] = total
            return loc
        except OSError:
            return None

    def _resolve_shm(self, locator: Dict[str, Any], cache_oid: ObjectID):
        """Resolve a locator to a frame buffer. Local node: a pinned
        zero-copy view (pin held by the store entry until freed — this is the
        'primary copy pinned' discipline that keeps numpy views into the
        mmap valid), falling back to the spill file. Remote node: chunked
        fetch via the node's object server with admission control."""
        if locator["node_id"] == self.node_id.binary():
            try:
                store = self._open_shm(locator["path"])
                view = store.get_view(locator["oid"])
            except OSError:  # store file gone (node supervisor died)
                view = None
            if view is not None:
                entry = self.store._entry(cache_oid)
                entry.shm_view = view
                # Read-only: sealed objects are immutable (plasma
                # semantics); numpy arrays deserialized over this buffer are
                # zero-copy views and must not scribble on the mapping.
                return view.data.toreadonly()
            spill = locator.get("spill")
            if spill is None:
                from ray_tpu.core.node import spill_file

                spill = spill_file(self.node_id, locator["oid"])
            try:
                with open(spill, "rb") as f:
                    return f.read()
            except OSError:
                raise ObjectLostError(
                    f"object {cache_oid.hex()} evicted from the local store"
                ) from None
        payload = self._pull_remote(locator, cache_oid)
        self.store.put_serialized(cache_oid, payload)
        return payload

    def _pull_remote_replicate(self, locator: Dict[str, Any],
                               cache_oid: ObjectID):
        """Broadcast-tree pull: fetch the object's chunks STRAIGHT into a
        buffer in THIS node's store (one copy on this host), seal it
        UNPINNED (LRU-evictable — replicas are cache, not primaries) and
        serve a zero-copy view. Returns (frame, new_locator|None); falls
        back to a plain in-process pull when the store has no room."""
        total = locator.get("total", 0)
        store = buf = None
        try:
            from ray_tpu.core.node import shm_store_path

            store = self._open_shm(shm_store_path(self.node_id))
            buf = store.create_buffer(cache_oid.binary(), total)
        except OSError:
            buf = None
        if buf is None:
            payload = self._pull_remote(locator, cache_oid)
            self.store.put_serialized(cache_oid, payload)
            return payload, None
        try:
            self._pull_remote_into(locator, cache_oid, buf, total)
        except BaseException:
            try:
                store.seal(cache_oid.binary(), pin=False)
                store.delete(cache_oid.binary())
            except Exception:  # graftlint: disable=swallowed-exception
                # Best-effort shm cleanup while the pull failure is
                # already propagating — must not mask it.
                pass
            raise
        store.seal(cache_oid.binary(), pin=False)
        view = store.get_view(cache_oid.binary())
        if view is None:  # evicted before we could even view it
            payload = self._pull_remote(locator, cache_oid)
            self.store.put_serialized(cache_oid, payload)
            return payload, None
        entry = self.store._entry(cache_oid)
        entry.shm_view = view
        loc = self._shm_locator(cache_oid)
        loc["total"] = total
        return view.data.toreadonly(), loc

    def _pull_remote_into(self, locator: Dict[str, Any],
                          cache_oid: ObjectID, buf, total: int,
                          start: int = 0) -> None:
        """Chunked pull written at-offset into ``buf`` from ``start``
        (disjoint ranges; parallel chunk threads never overlap), gated by
        the pull-slot memory budget (reference: ObjectManager 64 MiB chunk
        pulls, object_manager.h:117 / pull_manager.h:52). The remaining
        chunks fan out on a dedicated pool (NOT _io_pool: multi-ref get()
        already saturates that pool, and fanning out from inside it would
        deadlock)."""
        node_client = self.clients.get(tuple(locator["node_addr"]))
        chunk = config.object_transfer_chunk_bytes
        oid = locator["oid"]

        def fetch(offset: int) -> None:
            with self._pull_slots:
                got = node_client.call("read_shm_chunk", oid, offset, chunk)
            if got is None:
                raise ObjectLostError(
                    f"object {cache_oid.hex()} evicted from remote store "
                    f"mid-pull at offset {offset}")
            rtotal, data = got
            if rtotal != total:
                raise ObjectLostError(
                    f"object {cache_oid.hex()} size changed mid-pull")
            if config.core_metrics_enabled:
                from ray_tpu.core.coremetrics import OBJ_TRANSFER_BYTES

                OBJ_TRANSFER_BYTES.inc(float(len(data)))
            buf[offset:offset + len(data)] = data

        try:
            offsets = list(range(start, total, chunk))
            if offsets:
                list(self._chunk_pool().map(fetch, offsets))
        except (RpcError, RemoteCallError, TimeoutError) as e:
            raise ObjectLostError(
                f"node holding {cache_oid.hex()} unreachable: {e}") from e

    def _pull_remote(self, locator: Dict[str, Any],
                     cache_oid: ObjectID) -> bytes:
        """Chunked node-to-node pull into process memory. One chunk learns
        the size, the rest delegate to ``_pull_remote_into`` (same
        admission control and error mapping as the replicating path)."""
        node_client = self.clients.get(tuple(locator["node_addr"]))
        chunk = config.object_transfer_chunk_bytes
        try:
            with self._pull_slots:
                got = node_client.call("read_shm_chunk", locator["oid"], 0,
                                       chunk)
        except (RpcError, RemoteCallError, TimeoutError) as e:
            raise ObjectLostError(
                f"node holding {cache_oid.hex()} unreachable: {e}") from e
        if got is None:
            raise ObjectLostError(
                f"object {cache_oid.hex()} evicted from remote store "
                f"mid-pull at offset 0")
        total, data = got
        if config.core_metrics_enabled:
            from ray_tpu.core.coremetrics import OBJ_TRANSFER_BYTES

            OBJ_TRANSFER_BYTES.inc(float(len(data)))
        if total <= len(data):
            return bytes(data)
        buf = bytearray(total)
        buf[:len(data)] = data
        self._pull_remote_into(locator, cache_oid, buf, total,
                               start=len(data))
        return bytes(buf)

    # ------------------------------------------------------------ put/get

    def put(self, value: Any) -> ObjectRef:
        t0 = time.perf_counter()
        t0_wall = time.time()
        oid = ObjectID.from_random()
        self.store.mark_owned(oid)
        with serialization.capture_refs() as nested:
            total, write = serialization.build_frame(value)
        self.store.set_nested(oid, nested)  # pin refs inside the frame
        ref = None
        if total > config.inline_object_max_bytes:
            locator = self._try_put_frame(oid, total, write)
            if locator is not None:
                self.store.put_shm_ref(oid, locator)
                ref = ObjectRef(oid, self.addr)
        if ref is None:
            out = bytearray(total)
            write(out)
            self.store.put_serialized(oid, bytes(out))
            ref = ObjectRef(oid, self.addr)
        if config.core_metrics_enabled:
            from ray_tpu.core import coremetrics as cm

            cm.OBJ_PUT_BYTES.inc(float(total))
            cm.OBJ_PUT_S.observe(time.perf_counter() - t0)
            # Object-plane hop in the request's trace (no-op without an
            # active span): `ray_tpu timeline` shows a serve/RL request's
            # puts alongside its RPC and engine spans.
            tracing.record_span("object:put", t0_wall, time.time(),
                                bytes=total, oid=oid.hex()[:8])
        return ref

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        ref_list: List[ObjectRef] = [refs] if single else list(refs)
        for r in ref_list:
            if not isinstance(r, ObjectRef):
                raise TypeError(f"get() expects ObjectRef(s), got {type(r)}")
        if len(ref_list) > 1:
            pool = self._io_pool()
            values = list(pool.map(
                lambda r: self._get_one(r, timeout), ref_list))
        else:
            values = [self._get_one(r, timeout) for r in ref_list]
        return values[0] if single else values

    _io_pool_inst: Optional[ThreadPoolExecutor] = None
    _chunk_pool_inst: Optional[ThreadPoolExecutor] = None
    _io_pool_lock = threading.Lock()

    def _io_pool(self) -> ThreadPoolExecutor:
        with self._io_pool_lock:
            if self._io_pool_inst is None:
                self._io_pool_inst = ThreadPoolExecutor(
                    max_workers=16, thread_name_prefix="core-io")
            return self._io_pool_inst

    def _chunk_pool(self) -> ThreadPoolExecutor:
        with self._io_pool_lock:
            if self._chunk_pool_inst is None:
                self._chunk_pool_inst = ThreadPoolExecutor(
                    max_workers=8, thread_name_prefix="chunk-pull")
            return self._chunk_pool_inst

    def _get_one(self, ref: ObjectRef, timeout: Optional[float]):
        if not config.core_metrics_enabled:
            frame = self._get_frame(ref, timeout)
            value = serialization.deserialize(frame)
            if isinstance(value, TaskError):
                raise value
            return value
        t0 = time.perf_counter()
        t0_wall = time.time()
        local = (ref.owner_addr in (None, self.addr)
                 or self.store.is_ready(ref.id))
        frame = self._get_frame(ref, timeout)
        value = serialization.deserialize(frame)
        from ray_tpu.core import coremetrics as cmx

        path = "local" if local else "remote"
        cmx.OBJ_GET_S.observe(time.perf_counter() - t0, {"path": path})
        tracing.record_span("object:get", t0_wall, time.time(),
                            path=path, oid=ref.hex()[:8])
        if isinstance(value, TaskError):
            raise value
        return value

    def _get_frame(self, ref: ObjectRef, timeout: Optional[float]):
        """Fetch the serialized frame for ``ref``: local store (zero-copy shm
        view when the value lives in this node's store) or owner pull. Lost
        objects (evicted / node died) are reconstructed by re-executing the
        producing task when lineage is known (object_recovery_manager.h:41)."""
        if ref.owner_addr in (None, self.addr):
            attempts = 0
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            while True:
                left = (None if deadline is None
                        else max(0.0, deadline - time.monotonic()))
                entry = self.store.wait_ready(ref.id, left)
                try:
                    if entry.data is not None:
                        return entry.data
                    if entry.shm_ref is not None:
                        return self._resolve_shm(entry.shm_ref, ref.id)
                    raise ObjectLostError(
                        f"object {ref.hex()} has no data")
                except ObjectLostError:
                    attempts += 1
                    if (attempts > config.reconstruction_max_attempts
                            or not self._try_reconstruct(ref.id)):
                        raise
        if self.store.contains(ref.id):
            entry = self.store.wait_ready(ref.id, timeout)
            try:
                if entry.data is not None:
                    return entry.data
                if entry.shm_ref is not None:
                    return self._resolve_shm(entry.shm_ref, ref.id)
            except ObjectLostError:
                # Cached locator went stale (node died): drop the cache and
                # fall through to the owner pull below.
                self.store.drop(ref.id)
        # Borrower path: long-poll the owner, then resolve/cache locally.
        owner = self.clients.get(ref.owner_addr)
        recon_asked = 0
        src_fails = 0
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            step = 5.0 if deadline is None else min(5.0, deadline - time.monotonic())
            if step <= 0:
                from ray_tpu.core.errors import GetTimeoutError
                raise GetTimeoutError(f"object {ref.hex()} not ready in time")
            try:
                result = owner.call("get_object", ref.id.binary(), step,
                                    self.node_id.binary(),
                                    timeout=step + 10.0)
            except RemoteCallError as e:
                # The owner re-raised a stored error (put_error): surface the
                # real exception, not the transport wrapper.
                raise e.cause from None
            except (RpcError, TimeoutError) as e:
                raise ObjectLostError(
                    f"owner of {ref.hex()} at {ref.owner_addr} unreachable: {e}"
                ) from e
            if result is None:
                continue
            kind, payload = result
            if kind == "inline":
                self.store.put_serialized(ref.id, payload)
                return payload
            if kind == "shm":
                # src_key present = the owner leased us a broadcast pull
                # slot on that source (tree distribution); we must report
                # done/failed so the slot frees and our replica joins the
                # tree.
                src_key = payload.pop("src_key", None)
                slot_token = payload.pop("slot_token", None)
                remote = payload["node_id"] != self.node_id.binary()
                try:
                    if src_key is not None and remote:
                        frame, new_loc = self._pull_remote_replicate(
                            payload, ref.id)
                    else:
                        frame = self._resolve_shm(payload, ref.id)
                        new_loc = None
                except ObjectLostError:
                    self.store.drop(ref.id)
                    if src_key is not None:
                        try:
                            owner.notify("pull_failed", ref.id.binary(),
                                         src_key, payload["node_id"],
                                         slot_token)
                        except Exception:
                            # Owner unreachable: it will reap the pull
                            # slot by timeout instead.
                            log_every("runtime.pull_notify", 10.0, logger,
                                      "pull_failed notify to owner "
                                      "failed", exc_info=True)
                        src_fails += 1
                        if src_fails <= 3:
                            # A broadcast tree has alternative sources:
                            # the owner pruned the bad one, re-poll for
                            # another copy before escalating to lineage
                            # reconstruction. (Without a tree there is
                            # only the dead primary — reconstruct NOW.)
                            continue
                    recon_asked += 1
                    if recon_asked > config.reconstruction_max_attempts:
                        raise
                    try:
                        if not owner.call("reconstruct_object",
                                          ref.id.binary()):
                            raise
                    except (RpcError, RemoteCallError, TimeoutError):
                        raise ObjectLostError(
                            f"owner of {ref.hex()} unreachable for "
                            f"reconstruction") from None
                    continue
                if src_key is not None:
                    try:
                        owner.notify("pull_done", ref.id.binary(), src_key,
                                     new_loc, slot_token)
                    except Exception:
                        log_every("runtime.pull_notify", 10.0, logger,
                                  "pull_done notify to owner failed",
                                  exc_info=True)
                self.store.put_shm_ref(ref.id, new_loc or payload)
                return frame
            raise ObjectLostError(f"unknown get_object reply kind {kind!r}")

    def get_serialized(self, ref: ObjectRef, timeout: Optional[float]) -> bytes:
        """Like _get_frame but always materializes bytes (for RPC shipping)."""
        frame = self._get_frame(ref, timeout)
        return frame if isinstance(frame, bytes) else bytes(frame)

    def wait_ready(self, ref: ObjectRef, timeout: Optional[float]) -> None:
        """Block until ``ref`` is ready, without transferring its value —
        used by owner-side dependency resolution (dependency_resolver.h
        resolves availability, not bytes)."""
        if self.store.contains(ref.id) or ref.owner_addr in (None, self.addr):
            self.store.wait_ready(ref.id, timeout)
            return
        owner = self.clients.get(ref.owner_addr)
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            step = 5.0 if deadline is None else min(5.0, deadline - time.monotonic())
            if step <= 0:
                from ray_tpu.core.errors import GetTimeoutError

                raise GetTimeoutError(f"object {ref.hex()} not ready in time")
            try:
                if owner.call("wait_object", ref.id.binary(), step,
                              timeout=step + 10.0):
                    return
            except RemoteCallError as e:
                raise e.cause from None
            except (RpcError, TimeoutError) as e:
                raise ObjectLostError(
                    f"owner of {ref.hex()} at {ref.owner_addr} unreachable: {e}"
                ) from e

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None):
        ids = [r.id for r in refs]
        by_id = {r.id: r for r in refs}

        def poll(oid: ObjectID) -> bool:
            ref = by_id[oid]
            if ref.owner_addr in (None, self.addr):
                return False
            try:
                ready = self.clients.get(ref.owner_addr).call(
                    "peek_object", oid.binary(), timeout=5.0)
            except (RpcError, TimeoutError):
                return False
            return bool(ready)

        ready_ids, pending_ids = wait_any(
            self.store, ids, num_returns, timeout, poll=poll)
        return ([by_id[i] for i in ready_ids], [by_id[i] for i in pending_ids])

    # ------------------------------------------------- owned-object server

    def _handle_get_object(self, oid_bytes: bytes, timeout: float,
                           borrower_node: Optional[bytes] = None):
        """Long-poll: returns ("inline", frame) / ("shm", locator), or None
        on timeout. Owners hand out shm locators rather than bytes so the
        borrower can read node-locally (owner-based object directory,
        ownership_based_object_directory.h).

        Big objects (>= object_broadcast_min_bytes) distribute as a
        binomial TREE: the owner caps concurrent pulls per source copy
        (object_broadcast_fanout) and pullers that finish register their
        node's copy as a new source (``pull_done``), so N-node broadcast
        costs O(log N) serial transfer rounds instead of N pulls off one
        node (reference envelope: 1 GiB -> 50+ nodes,
        release/benchmarks/README.md:20; push dedup push_manager.h:30)."""
        oid = ObjectID(oid_bytes)
        deadline = time.monotonic() + timeout
        try:
            entry = self.store.wait_ready(oid, timeout)
        except Exception as e:
            from ray_tpu.core.errors import GetTimeoutError
            if isinstance(e, GetTimeoutError):
                return None
            raise
        primary = entry.shm_ref
        if primary is None:
            if entry.data is None:
                raise ObjectLostError(f"object {oid.hex()} has no data")
            return ("inline", entry.data)
        total = primary.get("total", 0)
        if (config.object_broadcast_fanout <= 0
                or total < config.object_broadcast_min_bytes):
            return ("shm", primary)
        return self._assign_pull_source(oid_bytes, primary, borrower_node,
                                        deadline)

    def _assign_pull_source(self, oid_bytes: bytes, primary: Dict[str, Any],
                            borrower_node: Optional[bytes],
                            deadline: float):
        """Pick a source copy with a free pull slot, blocking (within the
        long-poll window) until one frees. Same-node copies need no slot —
        they are zero-copy local reads."""
        fanout = max(1, config.object_broadcast_fanout)
        lease = config.object_pull_slot_lease_s
        with self._bcast_cond:
            track = self._bcast.setdefault(
                oid_bytes, {"secondaries": {}, "slots": {}})
            while True:
                locs = {primary["node_id"]: primary}
                locs.update(track["secondaries"])
                if borrower_node is not None and borrower_node in locs:
                    return ("shm", locs[borrower_node])  # local: no slot
                now = time.monotonic()
                best_key, best_load = None, None
                for key, loc in locs.items():
                    live = {tok: t
                            for tok, t in track["slots"].get(key, {}).items()
                            if t > now}
                    track["slots"][key] = live
                    if len(live) < fanout and (best_load is None
                                               or len(live) < best_load):
                        best_key, best_load = key, len(live)
                if best_key is not None:
                    # Per-grant token: done/failed releases THIS lease, so
                    # a pull completing past its expiry (already pruned)
                    # can't pop another puller's live slot and transiently
                    # exceed the fanout budget.
                    token = os.urandom(8)
                    track["slots"].setdefault(best_key, {})[token] = (
                        now + lease)
                    loc = dict(locs[best_key])
                    loc["src_key"] = best_key
                    loc["slot_token"] = token
                    return ("shm", loc)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None  # borrower re-polls
                self._bcast_cond.wait(min(remaining, 1.0))

    def _release_pull_slot_locked(self, track: Dict[str, Any],
                                  src_key: bytes,
                                  slot_token: Optional[bytes]) -> None:
        slots = track["slots"].get(src_key)
        if not slots:
            return
        if slot_token is not None:
            slots.pop(slot_token, None)  # absent = already expiry-pruned
        else:
            slots.pop(next(iter(slots)), None)

    def _handle_pull_done(self, oid_bytes: bytes, src_key: bytes,
                          new_locator: Optional[Dict[str, Any]],
                          slot_token: Optional[bytes] = None) -> None:
        """A puller finished: release its source slot and (when it managed
        to replicate into its node's store) add that copy as a new source."""
        with self._bcast_cond:
            track = self._bcast.get(oid_bytes)
            if track is None:
                return
            self._release_pull_slot_locked(track, src_key, slot_token)
            if new_locator is not None:
                track["secondaries"][new_locator["node_id"]] = new_locator
            self._bcast_cond.notify_all()

    def _handle_pull_failed(self, oid_bytes: bytes,
                            src_key: Optional[bytes],
                            bad_key: bytes,
                            slot_token: Optional[bytes] = None) -> None:
        """A source failed mid-pull/read: release the leased slot (when one
        was leased — local reads lease none) and forget the secondary (a
        dead PRIMARY is the reconstruction path's business)."""
        with self._bcast_cond:
            track = self._bcast.get(oid_bytes)
            if track is None:
                return
            if src_key is not None:
                self._release_pull_slot_locked(track, src_key, slot_token)
            track["secondaries"].pop(bad_key, None)
            self._bcast_cond.notify_all()

    def _handle_wait_object(self, oid_bytes: bytes, timeout: float) -> bool:
        try:
            self.store.wait_ready(ObjectID(oid_bytes), timeout)
            return True
        except Exception as e:
            from ray_tpu.core.errors import GetTimeoutError
            if isinstance(e, GetTimeoutError):
                return False
            return True  # ready-with-error counts as ready

    def _handle_peek_object(self, oid_bytes: bytes) -> bool:
        return self.store.is_ready(ObjectID(oid_bytes))

    def _handle_free_object(self, oid_bytes: bytes) -> None:
        self.free_object(ObjectID(oid_bytes))

    # -------------------------------------------- distributed ref counting

    def _handle_ref_update(self, deltas: Dict[bytes, int]) -> None:
        self.apply_ref_updates(deltas)

    def apply_ref_updates(self, deltas: Dict[bytes, int]) -> None:
        for oid_bytes, delta in deltas.items():
            self.store.apply_ref_update(ObjectID(oid_bytes), delta)

    def _sweep_loop(self) -> None:
        """Owner-side lifetime sweeper: frees owned objects whose
        cluster-wide handle count has stayed at zero past the grace period
        (reference: ReferenceCounter deleting out-of-scope objects,
        reference_count.h:61). Doubles as the task-event flusher."""
        while not self._shutdown.wait(max(0.2, config.ref_free_grace_s / 4)):
            try:
                if config.ref_counting_enabled:
                    for oid, _loc in self.store.sweep_dead_refs(
                            config.ref_free_grace_s):
                        self.free_object(oid)
                    # Freed tombstones don't live forever (a long-running
                    # owner would otherwise accumulate one per dead object).
                    self.store.purge_freed(max(60.0,
                                               config.ref_free_grace_s * 30))
                self._flush_task_events()
            except Exception:
                # A sweeper that dies silently means owned objects are
                # never freed — keep the loop alive but leave a trail.
                log_every("runtime.sweep", 30.0, logger,
                          "ref sweeper pass failed", exc_info=True)

    def record_task_event(self, event: Dict[str, Any]) -> None:
        with self._task_events_lock:
            self._task_events.append(event)
            if len(self._task_events) > config.event_buffer_max:
                del self._task_events[:len(self._task_events) // 2]

    def _flush_task_events(self) -> None:
        with self._task_events_lock:
            events, self._task_events = self._task_events, []
        if events:
            try:
                self.controller.notify("push_task_events", events)
            except Exception:
                log_every("runtime.task_events", 30.0, logger,
                          "task-event flush (%d events) failed",
                          len(events), exc_info=True)

    def free_object(self, oid: ObjectID) -> None:
        """Full owner-side free: in-process entry, primary shm copy (pin +
        slot), spill file, and lineage."""
        with self.store._lock:
            entry = self.store._entries.get(oid)
            locator = entry.shm_ref if entry is not None else None
        self.store.free(oid)
        if locator is not None:
            try:
                self.clients.get(tuple(locator["node_addr"])).notify(
                    "free_shm_object", locator["oid"])
            except Exception:
                # Usually the node is simply gone (its store died with
                # it); a live node failing frees would leak shm slots.
                log_every("runtime.free_shm", 30.0, logger,
                          "free of primary shm copy failed",
                          exc_info=True)
        with self._bcast_cond:
            track = self._bcast.pop(oid.binary(), None)
        if track:
            # Secondary copies are unpinned (LRU-evictable), but free them
            # eagerly anyway — a freed object's replicas are pure waste.
            for loc in track["secondaries"].values():
                try:
                    self.clients.get(tuple(loc["node_addr"])).notify(
                        "free_shm_object", loc["oid"])
                except Exception:
                    log_every("runtime.free_shm", 30.0, logger,
                              "free of replica shm copy failed",
                              exc_info=True)
        with self._lineage_lock:
            self._lineage.pop(oid, None)

    # ---------------------------------------------- lineage/reconstruction

    def record_lineage(self, return_ids: List[ObjectID],
                       spec: Dict[str, Any], options: Dict[str, Any]) -> None:
        """Owner-kept lineage: remember how to re-produce these objects
        (reference: TaskManager lineage, task_manager.h:215). Bounded FIFO."""
        record = {"spec": spec, "options": options,
                  "return_ids": list(return_ids), "lock": threading.Lock(),
                  "attempts": 0}
        with self._lineage_lock:
            for oid in return_ids:
                self._lineage[oid] = record
            while len(self._lineage) > config.max_lineage_entries:
                self._lineage.pop(next(iter(self._lineage)))

    def _try_reconstruct(self, oid: ObjectID) -> bool:
        """Re-execute the producing task of a lost object (reference:
        ObjectRecoveryManager, object_recovery_manager.h:41,96-106). Returns
        False when no lineage is known (e.g. a put object)."""
        with self._lineage_lock:
            record = self._lineage.get(oid)
        if record is None:
            return False
        with record["lock"]:
            # If another thread already reset this entry, just wait on it.
            if not self.store.is_ready(oid):
                return True
            if record["attempts"] >= config.reconstruction_max_attempts:
                return False
            record["attempts"] += 1
            for rid in record["return_ids"]:
                self.store.reset_pending(rid)
            arg_refs = _collect_top_level_refs(
                *serialization.deserialize(record["spec"]["args_blob"]))
            self.submitter.submit(record["spec"], record["options"],
                                  record["return_ids"], arg_refs)
        return True

    def _handle_reconstruct(self, oid_bytes: bytes) -> bool:
        """Borrower-requested reconstruction of an owned object."""
        return self._try_reconstruct(ObjectID(oid_bytes))

    # -------------------------------------------------- task submission

    def submit_task(self, func_key: str, desc: str,
                    args: tuple, kwargs: dict, options: Dict[str, Any]
                    ) -> List[ObjectRef]:
        task_id = TaskID.from_random()
        num_returns = options.get("num_returns", 1)
        streaming = num_returns == "streaming"
        if streaming:
            num_returns = 0
        return_ids = [ObjectID.from_random() for _ in range(num_returns)]
        refs = [ObjectRef(oid, self.addr) for oid in return_ids]
        for oid in return_ids:
            self.store.create_pending(oid)
        arg_refs = _collect_top_level_refs(args, kwargs)
        # Function body travels via the controller KV (exported once per
        # cluster, fetched once per worker) — not with every task spec.
        # All refs pickled into args (any nesting depth) are captured and
        # kept alive by the submitter until the task replies, so the owner
        # can't free them while the task is in flight.
        with serialization.capture_refs() as held_refs:
            args_blob = serialization.serialize((args, kwargs))
        spec = {
            "task_id": task_id.binary(),
            "func_key": func_key,
            "desc": desc,
            "args_blob": args_blob,
            "return_ids": [o.binary() for o in return_ids],
            "owner_addr": self.addr,
        }
        if not options.get("inline_results", True):
            spec["force_shm"] = True
        from ray_tpu.util import tracing

        trace_ctx = tracing.context_for_spec()
        if trace_ctx is not None:
            spec["trace"] = trace_ctx
        if streaming:
            spec["streaming"] = True
            self._stream_state(task_id.binary())  # exists before items land
            self.submitter.submit(spec, options, return_ids, arg_refs,
                                  held_refs)
            return ObjectRefGenerator(self, task_id.binary(), desc)
        if options.get("max_retries", 3) > 0:
            self.record_lineage(return_ids, spec, options)
        self.submitter.submit(spec, options, return_ids, arg_refs,
                              held_refs)
        return refs

    # ---------------------------------------------------- task execution

    def _load_function(self, func_key: str, func_blob: Optional[bytes]):
        with self._fn_cache_lock:
            fn = self._fn_cache.get(func_key)
        if fn is not None:
            return fn
        if func_blob is None:
            func_blob = self.controller.call("kv_get", func_key)
            if func_blob is None:
                raise RayTpuError(f"function {func_key} not found in KV")
        fn = serialization.loads_function(func_blob)
        with self._fn_cache_lock:
            self._fn_cache[func_key] = fn
        return fn

    def _resolve_args(self, args_blob: bytes):
        args, kwargs = serialization.deserialize(args_blob)
        args = tuple(
            self._get_one(a, None) if isinstance(a, ObjectRef) else a
            for a in args)
        kwargs = {
            k: self._get_one(v, None) if isinstance(v, ObjectRef) else v
            for k, v in kwargs.items()}
        return args, kwargs

    def _handle_push_task(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        """Execute a normal task; reply carries serialized results.

        Reference: the PushTask execution path in ``_raylet.pyx:2259``
        (task_execution_handler) minus the Cython; results return in-band to
        the owner (reference inlines <100KB returns the same way)."""
        # A push that arrives near/past the lease-reclamation window may
        # race a reclaim-and-re-grant: running it would execute two leases'
        # tasks concurrently on one pooled worker (resources double-booked).
        # Validate against the node's CURRENT lease_seq only in that rare
        # late window — the common path (push within seconds of the grant,
        # which reclamation provably cannot have touched) stays RPC-free.
        lease_seq = spec.get("lease_seq")
        lease_ts = spec.get("lease_ts")  # node monotonic; same host as us
        if (lease_seq is not None and lease_ts is not None
                and config.lease_undelivered_timeout_s > 0
                and time.monotonic() - lease_ts
                > max(0.5, config.lease_undelivered_timeout_s - 2.0)):
            try:
                still_mine = self.clients.get(self.node_addr).call(
                    "validate_lease", self.worker_id.binary(), lease_seq,
                    timeout=5.0)
            except Exception:
                still_mine = True  # node unreachable: keep pre-check behavior
            if not still_mine:
                return {"ok": False, "stale_lease": True}
        self.tasks_received += 1
        self.active_tasks += 1
        try:
            fn = self._load_function(spec["func_key"], spec.get("func_blob"))
            args, kwargs = self._resolve_args(spec["args_blob"])
            self._current_task_desc.value = spec.get("desc", "")
            from ray_tpu.util import tracing

            with tracing.activate(spec.get("trace"),
                                  name=f"task:{spec.get('desc', '')}"):
                result = fn(*args, **kwargs)
                if spec.get("streaming"):
                    # Streaming-generator task: push each yielded item to
                    # the owner as it is produced (reference: streaming
                    # returns, ReportGeneratorItemReturns); the reply
                    # carries only the final count. Iteration runs the
                    # USER's generator body, so it stays inside the trace
                    # context.
                    owner = self.clients.get(spec["owner_addr"])
                    count = 0
                    for item in result:
                        owner.call("stream_item", spec["task_id"], count,
                                   self._pack_results([item])[0])
                        count += 1
                    return {"ok": True, "results": [],
                            "stream_len": count}
            n = len(spec["return_ids"])
            if n == 0:
                results = []
            elif n == 1:
                results = [result]
            else:
                result = tuple(result)
                if len(result) != n:
                    raise ValueError(
                        f"task {spec['desc']} declared num_returns={n} but "
                        f"returned {len(result)} values")
                results = list(result)
            return {"ok": True, "results": self._pack_results(
                results, force_shm=spec.get("force_shm", False))}
        except BaseException as e:  # noqa: BLE001 — shipped to the owner
            err = TaskError(e, task_desc=spec.get("desc", ""))
            return {"ok": False,
                    "error_frame": serialization.serialize(err)}
        finally:
            self._current_task_desc.value = None
            self.active_tasks -= 1

    def _handle_push_task_batch(self, specs: List[Dict[str, Any]]):
        """Execute a pipelined batch serially on this worker: one RPC for
        N same-lease tasks (the owner's lease-pipelining runner batches
        small ready tasks — per-task RPC overhead is the throughput
        ceiling for fine-grained work). All specs share one lease; the
        late-push staleness check runs once."""
        first = specs[0]
        lease_seq = first.get("lease_seq")
        lease_ts = first.get("lease_ts")
        if (lease_seq is not None and lease_ts is not None
                and config.lease_undelivered_timeout_s > 0
                and time.monotonic() - lease_ts
                > max(0.5, config.lease_undelivered_timeout_s - 2.0)):
            try:
                still_mine = self.clients.get(self.node_addr).call(
                    "validate_lease", self.worker_id.binary(), lease_seq,
                    timeout=5.0)
            except Exception:
                still_mine = True
            if not still_mine:
                return {"stale_lease": True}
        replies = []
        for spec in specs:
            spec.pop("lease_seq", None)  # checked once above
            spec.pop("lease_ts", None)
            replies.append(self._handle_push_task(spec))
        return replies

    def _pack_results(self, results: List[Any],
                      force_shm: bool = False) -> List[tuple]:
        """Serialize task returns; large frames go into this node's shm store
        and ship as locators (reference: small returns in-band to the owner's
        memory store, large returns plasma-put — core_worker task reply
        path). Each element is ("inline", bytes, nested_refs) or
        ("shm", locator, nested_refs); nested_refs are the ObjectRefs pickled
        inside the frame — the owner pins them for the frame's lifetime.

        ``force_shm`` (task option ``inline_results=False``) routes even
        small returns through the node store: an all-to-all exchange emits
        P^2 sub-threshold slices whose inline copies would otherwise pile
        up O(dataset) in the owner's heap while the exchange is in flight
        (the reference keeps shuffle chunks in plasma for the same
        reason)."""
        packed = []
        for r in results:
            with serialization.capture_refs() as nested:
                total, write = serialization.build_frame(r)
            if force_shm or total > config.inline_object_max_bytes:
                oid = ObjectID.from_random()
                locator = self._try_put_frame(oid, total, write)
                if locator is not None:
                    packed.append(("shm", locator, nested))
                    continue
            out = bytearray(total)
            write(out)
            packed.append(("inline", bytes(out), nested))
        return packed

    # ------------------------------------------------ streaming generators

    def _stream_state(self, task_id: bytes) -> Optional[Dict[str, Any]]:
        """Live stream state, creating it on first touch. ``None`` means
        the consumer dropped the stream (tombstone): late pushes must NOT
        resurrect it (they would pin refs forever)."""
        with self._streams_cond:
            if task_id in self._streams:
                return self._streams[task_id]  # may be a None tombstone
            state = {"items": {}, "arrived": set(), "total": None,
                     "error": None}
            self._streams[task_id] = state
            return state

    def _handle_stream_item(self, task_id: bytes, index: int,
                            packed: tuple) -> None:
        """Owner-side: one yielded item from a streaming-generator task
        (reference: ReportGeneratorItemReturns, core_worker.proto — items
        stream back before the task finishes). The arrival check-and-claim
        is atomic under the stream condition, so concurrent duplicate
        pushes (original worker + retry) fulfil each index exactly once."""
        state = self._stream_state(task_id)
        if state is None:
            return  # consumer dropped the stream; discard late pushes
        with self._streams_cond:
            if index in state["arrived"]:
                return  # duplicate from a retry
            state["arrived"].add(index)
        oid = ObjectID(
            hashlib.sha256(task_id + index.to_bytes(4, "little")).digest()
            [:ObjectID.NBYTES])
        self.store.create_pending(oid)
        self.fulfil_result(oid, packed)
        with self._streams_cond:
            # Holding the ref in the state keeps the item alive until the
            # consumer takes it (the ref sweeper frees unreferenced ids).
            state["items"][index] = ObjectRef(oid, self.addr)
            self._streams_cond.notify_all()

    def _finish_stream(self, task_id: bytes, total: Optional[int],
                       error: Optional[BaseException]) -> None:
        state = self._stream_state(task_id)
        if state is None:
            return
        with self._streams_cond:
            state["total"] = (total if total is not None
                              else len(state["arrived"]))
            state["error"] = error
            self._streams_cond.notify_all()

    def stream_next(self, task_id: bytes, index: int,
                    timeout: Optional[float] = None):
        """Block until item ``index`` exists; returns its ObjectRef or
        raises StopIteration/the task error. Single-consumer: the handed-
        over ref is removed from the state (the caller's ref is the live
        handle), so consumed items free as the consumer releases them
        instead of accumulating for the stream's lifetime."""
        state = self._stream_state(task_id)
        if state is None:
            raise StopIteration
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._streams_cond:
            while True:
                if index in state["items"]:
                    return state["items"].pop(index)
                if state["error"] is not None:
                    raise state["error"]
                if state["total"] is not None and index >= state["total"]:
                    raise StopIteration
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    from ray_tpu.core.errors import GetTimeoutError

                    raise GetTimeoutError(
                        f"stream item {index} not ready in {timeout}s")
                self._streams_cond.wait(
                    1.0 if remaining is None else min(remaining, 1.0))

    def drop_stream(self, task_id: bytes) -> None:
        """Release a stream's state (its held item refs free via the normal
        refcount path) — called when the consuming generator is GC'd. A
        bounded tombstone remains so late pushes from the still-running
        task are discarded instead of resurrecting the state."""
        with self._streams_cond:
            self._streams[task_id] = None
            tombstones = [k for k, v in self._streams.items() if v is None]
            for k in tombstones[:-256]:
                del self._streams[k]

    def fulfil_result(self, oid: ObjectID, packed: tuple) -> None:
        """Owner-side: record a packed task result; refs nested in the frame
        (already re-materialized by the RPC deserializer, so their handles
        are registered) stay pinned by the entry."""
        kind, payload = packed[0], packed[1]
        if len(packed) > 2 and packed[2]:
            self.store.set_nested(oid, packed[2])
        if kind == "shm":
            self.store.put_shm_ref(oid, payload)
        else:
            self.store.put_serialized(oid, payload)

    # -------------------------------------------------------- actor side

    def _handle_start_actor(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        self.tasks_received += 1
        # active_tasks covers the WHOLE __init__: the node's lease reaper
        # must see this worker as busy while a slow constructor (model
        # load) runs, or it would reclaim a delivered actor lease.
        self.active_tasks += 1
        try:
            cls = self._load_function(spec["cls_key"], spec.get("cls_blob"))
            args, kwargs = self._resolve_args(spec["args_blob"])
            instance = cls(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001
            err = TaskError(e, task_desc=f"{spec.get('desc', '')}.__init__")
            return {"ok": False, "error_frame": serialization.serialize(err)}
        finally:
            self.active_tasks -= 1
        self._actor_runtime = ActorExecutionRuntime(
            self, instance,
            max_concurrency=spec.get("max_concurrency", 1),
        )
        return {"ok": True}

    def _handle_push_actor_task(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        if self._actor_runtime is None:
            raise ActorDiedError(reason="actor not started on this worker")
        return self._actor_runtime.execute(spec)

    def _handle_shutdown(self) -> None:
        self._shutdown.set()

    # --------------------------------------------------------- lifecycle

    def shutdown(self) -> None:
        self._shutdown.set()
        self.submitter.stop()
        self.controller.close()
        self.clients.close_all()
        self.server.stop()


# --------------------------------------------------------------------------
# Submitter
# --------------------------------------------------------------------------


class TaskSubmitter:
    """Owner-side async task submitter (reference:
    ``CoreWorkerDirectTaskSubmitter``, direct_task_transport.h:75)."""

    def __init__(self, core: CoreWorker):
        self._core = core
        self._pool = ThreadPoolExecutor(max_workers=32,
                                        thread_name_prefix="submit")
        self._stopped = False
        # Lease pipelining: ready same-shape tasks queue here and a
        # BOUNDED set of runner threads drains them, each holding one
        # lease (see submit/_runner). Unbounded runners would degenerate
        # to one-lease-per-task (every pool thread grabs its own item).
        self._reuse_lock = threading.Lock()
        self._reuse_queues: Dict[tuple, deque] = {}
        self._runners: Dict[tuple, int] = {}

    _RUNNER_CAP = 16  # max concurrent pipelining leases per shape

    def submit(self, spec, options, return_ids: List[ObjectID],
               arg_refs: List[ObjectRef],
               held_refs: Optional[List[ObjectRef]] = None) -> None:
        # held_refs: every ref serialized into the args (incl. nested) —
        # passing them through the work item keeps their handles
        # registered until execution finishes, exactly the in-flight
        # window.
        core = self._core
        key = self._reuse_key(spec, options)
        # RETRIABLE items whose deps are ALREADY ready enter the shared
        # pipeline: runner threads execute queued items back-to-back on
        # leased workers (one push per task instead of
        # pick+lease+push+return). Anything with unresolved deps takes
        # the solo path, which may block on them without holding a lease
        # (the original no-lease-holding-deadlock rule); non-retriable
        # tasks also go solo — a reused worker that died since its last
        # task would convert their never-executed push into a terminal
        # crash, where the solo path's fresh lease gets a live worker.
        if (key is not None
                and options.get("max_retries", 3) > 0
                and options.get("retry_on_crash", True)
                and all(core.store.is_ready(r.id) for r in arg_refs)):
            item = (spec, options, return_ids, arg_refs, held_refs)
            with self._reuse_lock:
                q = self._reuse_queues.setdefault(key, deque())
                q.append(item)
                n_runners = self._runners.get(key, 0)
                spawn = (n_runners < self._RUNNER_CAP
                         and (n_runners == 0
                              or len(q) > 4 * n_runners))
                if spawn:
                    self._runners[key] = n_runners + 1
            if spawn:
                self._pool.submit(self._runner, key)
            return
        self._pool.submit(self._run_item, spec, options, return_ids,
                          arg_refs, held_refs, None, False)

    def stop(self) -> None:
        self._stopped = True
        self._pool.shutdown(wait=False, cancel_futures=True)

    def _fail(self, return_ids: List[ObjectID], err: BaseException) -> None:
        for oid in return_ids:
            self._core.store.put_error(oid, err)

    def _return_worker_safely(self, node_addr, worker_id, resources,
                              bundle, dead: bool,
                              lease_seq: Optional[int] = None) -> None:
        """Return a lease without letting a transport blip become the
        TASK's error: one fresh-socket retry, then give up — the node's
        reaper reclaims the lease anyway once the worker self-reports
        idle past lease_undelivered_timeout_s (_reclaim_undelivered_
        leases), so a doubly-lost return degrades to a short capacity dip,
        not a leak. The lease_seq makes the retry idempotent — a first
        attempt that was APPLIED but whose reply was lost cannot
        double-credit/double-pool (the node's generation check no-ops the
        duplicate)."""
        for attempt in range(2):
            try:
                self._core.clients.get(tuple(node_addr)).call(
                    "return_worker", worker_id, resources, bundle, dead,
                    lease_seq, timeout=10.0)
                return
            except (RpcError, RemoteCallError, TimeoutError):
                self._core.clients.invalidate(tuple(node_addr))

    # ------------------------------------------------ lease pipelining

    @staticmethod
    def _reuse_key(spec, options):
        """Tasks that can share a leased worker back-to-back (reference:
        direct_task_transport's lease reuse + pipelining): plain tasks
        only — no PG bundle, no scheduling strategy, no runtime env. The
        key is the resource shape the lease was granted for."""
        if (options.get("placement") is not None
                or options.get("scheduling_strategy") is not None
                or options.get("runtime_env") is not None):
            return None
        res = options.get("resources", {"CPU": 1.0})
        return tuple(sorted(res.items()))

    _BATCH_MAX = 16

    def _runner(self, key) -> None:
        """Pool entry for one pipelining runner (accounted in
        self._runners). The exit race — runner sees an empty queue and
        leaves exactly as an enqueuer declines to spawn because it saw
        this runner alive — is healed in the finally: the LAST runner out
        respawns itself if items remain."""
        try:
            self._drain_pipeline(key)
        finally:
            respawn = False
            with self._reuse_lock:
                self._runners[key] = self._runners.get(key, 1) - 1
                q = self._reuse_queues.get(key)
                if q and self._runners[key] == 0:
                    self._runners[key] = 1
                    respawn = True
            if respawn:
                self._pool.submit(self._runner, key)

    def _drain_pipeline(self, key) -> None:
        """Runner: pop queued same-shape items and execute them on ONE
        leased worker until the queue drains (then return the lease).
        Once a lease is held, RETRIABLE items ship as push_task_batch
        groups (one RPC per up-to-16 tasks). Concurrency comes from the
        pool: up to pool-width runners per shape, each with its own
        lease."""
        state = None
        try:
            while True:
                with self._reuse_lock:
                    q = self._reuse_queues.get(key)
                    item = q.popleft() if q else None
                if item is None:
                    return
                if state is None:
                    spec, options, return_ids, arg_refs, held_refs = item
                    state = self._run_item(spec, options, return_ids,
                                           arg_refs, held_refs, None,
                                           True)
                    continue
                def batchable(it):
                    # Non-retriable tasks never batch (a mid-batch crash
                    # can't attribute execution); streaming replies need
                    # the solo reply shape.
                    return (it[1].get("max_retries", 3) > 0
                            and it[1].get("retry_on_crash", True)
                            and not it[0].get("streaming"))

                batch = [item]
                if batchable(item):
                    with self._reuse_lock:
                        q = self._reuse_queues.get(key)
                        while (q and len(batch) < self._BATCH_MAX
                               and batchable(q[0])):
                            batch.append(q.popleft())
                if len(batch) == 1:
                    spec, options, return_ids, arg_refs, held_refs = item
                    state = self._run_item(spec, options, return_ids,
                                           arg_refs, held_refs, state,
                                           True)
                else:
                    state = self._push_batch(batch, state)
        finally:
            if state is not None:
                self._return_worker_safely(
                    state["node_addr"], state["worker_id"],
                    state["resources"], None, False, state["lease_seq"])

    def _push_batch(self, batch, state):
        """Ship a batch of retriable items to the held worker in one RPC.
        Any transport failure or stale lease falls back to per-item solo
        execution (their normal retry budgets intact)."""
        core = self._core
        t_submit = time.time()
        specs = []
        for spec, _o, _r, _a, _h in batch:
            spec["lease_seq"] = state["lease_seq"]
            spec["lease_ts"] = state["lease_ts"]
            specs.append(spec)
        try:
            replies = core.clients.get(state["worker_addr"]).call(
                "push_task_batch", specs, timeout=None)
        except (RpcError, RemoteCallError, TimeoutError):
            self._return_worker_safely(
                state["node_addr"], state["worker_id"],
                state["resources"], None, True, state["lease_seq"])
            core.clients.invalidate(state["worker_addr"])
            self._resubmit_solo(batch)
            return None
        if isinstance(replies, dict) and replies.get("stale_lease"):
            self._resubmit_solo(batch)
            return None
        t_done = time.time()
        worker_hex = WorkerID(state["worker_id"]).hex()
        for (spec, _o, return_ids, _a, _h), reply in zip(batch, replies):
            if reply["ok"]:
                for oid, packed in zip(return_ids, reply["results"]):
                    core.fulfil_result(oid, packed)
            else:
                for oid in return_ids:
                    core.store.put_serialized(oid, reply["error_frame"])
            core.record_task_event({
                "task_id": TaskID(spec["task_id"]).hex(),
                "desc": spec.get("desc", ""),
                "state": "FINISHED" if reply["ok"] else "FAILED",
                "submitted_ts": t_submit, "lease_ts": t_submit,
                "end_ts": t_done, "worker": worker_hex,
                "owner": core.addr,
                "trace_id": (spec.get("trace") or {}).get("trace_id")})
        return state

    def _resubmit_solo(self, batch) -> None:
        for spec, options, return_ids, arg_refs, held_refs in batch:
            self._pool.submit(self._run_item, spec, options, return_ids,
                              arg_refs, held_refs, None, False)

    def _run_item(self, spec, options, return_ids, arg_refs,
                  held_refs, state, keep_lease: bool):
        """Execute one task. ``state`` (from a previous item) short-cuts
        pick+lease and pushes straight to the already-leased worker; any
        failure there falls back to the full path with normal retry
        semantics. Returns the (possibly new) lease state when
        ``keep_lease`` and the push succeeded, else None."""
        core = self._core
        t_submit = time.time()
        t_lease = t_run = None
        worker_hex = None
        new_state = None
        try:
            # 1. Resolve dependencies BEFORE leasing a worker
            #    (dependency_resolver.h — avoids lease-holding deadlock).
            #    Readiness only; the executor pulls values itself.
            for ref in arg_refs:
                core.wait_ready(ref, None)
            retries_left = options.get("max_retries", 3)
            excluded: List[bytes] = []
            lease_attempts = 0
            stale_leases = 0
            deadline = time.monotonic() + config.worker_lease_timeout_s
            while True:
                reused = state is not None
                if reused:
                    # Lease-reuse fast path: the runner already holds a
                    # compatible worker.
                    node_addr = state["node_addr"]
                    worker_id = state["worker_id"]
                    worker_addr = state["worker_addr"]
                    lease_seq = state["lease_seq"]
                    lease_ts_val = state["lease_ts"]
                    bundle = None
                    node_client = core.clients.get(tuple(node_addr))
                    state = None  # consumed; errors below re-lease fresh
                else:
                    # 2. Cluster-level node selection. Transport errors to
                    #    the controller (lossy network, head blip) are
                    #    retried within the lease deadline like any other
                    #    transient — the ReconnectingClient reopens the
                    #    socket underneath.
                    placement = options.get("placement")
                    picked_node_id: Optional[bytes] = None
                    try:
                        if placement is not None:
                            target = core.controller.call(
                                "get_placement_group", placement[0])
                        else:
                            pick = core.controller.call(
                                "pick_node",
                                options.get("resources", {"CPU": 1.0}),
                                options.get("scheduling_strategy"),
                                core.node_id.binary(), excluded)
                    except (RpcError, TimeoutError):
                        if time.monotonic() > deadline:
                            raise
                        time.sleep(0.2)
                        continue
                    if placement is not None:
                        if (target is None
                                or placement[1] not in target["placement"]):
                            raise RayTpuError(
                                f"placement group bundle {placement} "
                                f"not ready")
                        node_addr = target["placement"][placement[1]][1]
                        bundle = (placement[0], placement[1])
                    else:
                        if pick is None:
                            if time.monotonic() > deadline:
                                raise RayTpuError(
                                    f"no feasible node for resources "
                                    f"{options.get('resources')}")
                            time.sleep(0.2)
                            excluded = []
                            continue
                        node_addr = pick["addr"]
                        picked_node_id = pick["node_id"]
                        bundle = None
                    # 3. Worker lease from the chosen node. Transport
                    #    errors (node died between pick and lease) count
                    #    as lease failures: exclude the node and re-pick.
                    # Spillback (reference: hybrid_scheduling_policy.cc
                    # redirects): the first two lease attempts use a SHORT
                    # patience — if the picked node is busy, the quick
                    # "lease timeout" reply excludes it and re-picks
                    # another node instead of queueing behind a stale
                    # choice. Later attempts wait out the owner's
                    # remaining deadline (genuinely saturated cluster).
                    # Both are clamped to that deadline.
                    remaining = max(0.2, deadline - time.monotonic())
                    early_attempt = lease_attempts < 2 and bundle is None
                    patience = (min(5.0, remaining) if early_attempt
                                else remaining)
                    lease_attempts += 1
                    try:
                        node_client = core.clients.get(node_addr)
                        lease = node_client.call(
                            "lease_worker",
                            options.get("resources", {"CPU": 1.0}),
                            bundle, patience, False,
                            options.get("runtime_env"),
                            {"retriable": retries_left > 0
                                and options.get("retry_on_crash", True),
                             "owner": core.node_id.hex()},
                            # Early attempts may be spillback-rejected by
                            # a backlogged node (re-pick elsewhere); later
                            # attempts settle into the queue so a
                            # saturated or single-node cluster still makes
                            # progress.
                            early_attempt,
                            # Track the attempt's patience, not the global
                            # lease deadline: a LOST REPLY on a
                            # 5s-patience spillback probe must not block
                            # 40s (one lost packet would eat the whole
                            # lease budget).
                            timeout=patience + 10.0)
                    except (RpcError, RemoteCallError, TimeoutError) as e:
                        core.clients.invalidate(tuple(node_addr))
                        lease = {"error": f"node unreachable: {e}"}
                    if "error" in lease:
                        if picked_node_id is not None:
                            excluded.append(picked_node_id)
                        if (lease.get("permanent")
                                or time.monotonic() > deadline):
                            raise RayTpuError(
                                f"worker lease failed: {lease['error']}")
                        # PG-bundle leases don't go through the pick_node
                        # backoff above; sleep here so a busy node isn't
                        # RPC-hammered.
                        time.sleep(0.2)
                        continue
                    worker_id = lease["worker_id"]
                    worker_addr = lease["addr"]
                    lease_seq = lease.get("lease_seq")
                    lease_ts_val = lease.get("lease_ts")
                spec["lease_seq"] = lease_seq
                spec["lease_ts"] = lease_ts_val
                t_lease = time.time()
                worker_hex = WorkerID(worker_id).hex()
                # 4. Direct push to the leased worker.
                try:
                    reply = core.clients.get(worker_addr).call(
                        "push_task", spec, timeout=None)
                except (RpcError, RemoteCallError, TimeoutError) as e:
                    self._return_worker_safely(
                        node_addr, worker_id,
                        options.get("resources", {"CPU": 1.0}), bundle,
                        True, lease_seq)
                    core.clients.invalidate(worker_addr)
                    if (retries_left > 0
                            and options.get("retry_on_crash", True)):
                        retries_left -= 1
                        time.sleep(config.task_retry_delay_ms / 1000.0)
                        deadline = (time.monotonic()
                                    + config.worker_lease_timeout_s)
                        continue
                    # Terminal attempt: was this a node-initiated kill
                    # (memory monitor)? Surface the recorded cause
                    # instead of a generic crash.
                    try:
                        cause = node_client.call("worker_death_cause",
                                                 worker_id, timeout=2.0)
                    except Exception:
                        cause = None
                    if cause and "memory" in cause:
                        raise OutOfMemoryError(
                            f"task {spec['desc']} was killed by the node "
                            f"memory monitor: {cause}") from e
                    raise WorkerCrashedError(
                        f"worker died executing {spec['desc']}: {e}") from e
                if reply.get("stale_lease"):
                    # The node reclaimed this lease while the push crawled
                    # over the network; the worker refused to run it. The
                    # lease credit already happened at reclamation — take
                    # a fresh lease and push again, but BOUNDED: a link
                    # whose every push outlives the reclamation window
                    # would otherwise livelock here forever.
                    stale_leases += 1
                    if stale_leases > 5:
                        raise RayTpuError(
                            f"task {spec['desc']}: {stale_leases} leases "
                            "reclaimed before their push arrived — link "
                            "slower than lease_undelivered_timeout_s "
                            f"({config.lease_undelivered_timeout_s}s)")
                    time.sleep(0.2 * stale_leases)
                    deadline = (time.monotonic()
                                + config.worker_lease_timeout_s)
                    continue
                if keep_lease and bundle is None:
                    # The runner keeps this lease for the next queued
                    # item (returned below); the node sees continuous
                    # task progress through worker_ping, which exempts
                    # the lease from idle reclamation.
                    new_state = {"node_addr": node_addr,
                                 "worker_id": worker_id,
                                 "worker_addr": worker_addr,
                                 "lease_seq": lease_seq,
                                 "lease_ts": lease_ts_val,
                                 "resources": options.get(
                                     "resources", {"CPU": 1.0})}
                else:
                    # Best-effort with one fresh-socket retry: the task
                    # already SUCCEEDED — a lossy link must not convert a
                    # lost lease return into a task failure (the node's
                    # reaper re-credits the lease when the worker idles
                    # out or dies).
                    self._return_worker_safely(
                        node_addr, worker_id,
                        options.get("resources", {"CPU": 1.0}), bundle,
                        False, lease_seq)
                t_run = time.time()
                break
            # 5. Fulfil owned return objects.
            if reply["ok"]:
                for oid, packed in zip(return_ids, reply["results"]):
                    core.fulfil_result(oid, packed)
                if spec.get("streaming"):
                    core._finish_stream(spec["task_id"],
                                        reply.get("stream_len"), None)
            else:
                for oid in return_ids:
                    self._core.store.put_serialized(oid,
                                                    reply["error_frame"])
                if spec.get("streaming"):
                    core._finish_stream(
                        spec["task_id"], None,
                        serialization.deserialize(reply["error_frame"]))
            core.record_task_event({
                "task_id": TaskID(spec["task_id"]).hex(),
                "desc": spec.get("desc", ""),
                "state": "FINISHED" if reply["ok"] else "FAILED",
                "submitted_ts": t_submit, "lease_ts": t_lease,
                "end_ts": t_run, "worker": worker_hex,
                "owner": core.addr,
                "trace_id": (spec.get("trace") or {}).get("trace_id")})
            return new_state
        except BaseException as e:  # noqa: BLE001
            core.record_task_event({
                "task_id": TaskID(spec["task_id"]).hex(),
                "desc": spec.get("desc", ""), "state": "FAILED",
                "submitted_ts": t_submit, "lease_ts": t_lease,
                "end_ts": time.time(), "worker": worker_hex,
                "owner": core.addr, "error": repr(e)})
            self._fail(return_ids, e)
            if spec.get("streaming"):
                core._finish_stream(spec["task_id"], None, e)
            return None


class ObjectRefGenerator:
    """Iterator over a streaming-generator task's yielded ObjectRefs
    (reference: ``ObjectRefGenerator``/``StreamingObjectRefGenerator`` from
    ``num_returns="streaming"``). ``next()`` blocks until the next item has
    streamed back from the still-running task; iteration ends when the task
    returns, and raises the task's error if it failed."""

    def __init__(self, core: "CoreWorker", task_id: bytes, desc: str):
        self._core = core
        self._task_id = task_id
        self._desc = desc
        self._index = 0

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> ObjectRef:
        ref = self._core.stream_next(self._task_id, self._index)
        self._index += 1
        return ref

    def next_ready(self, timeout: float) -> ObjectRef:
        """Like ``next()`` but bounded by ``timeout`` (GetTimeoutError)."""
        ref = self._core.stream_next(self._task_id, self._index, timeout)
        self._index += 1
        return ref

    def __repr__(self) -> str:
        return (f"ObjectRefGenerator({self._desc}, "
                f"consumed={self._index})")

    def __del__(self):
        core = getattr(self, "_core", None)
        if core is not None:
            try:
                core.drop_stream(self._task_id)
            except Exception:  # graftlint: disable=swallowed-exception
                # __del__ during interpreter teardown: anything (even
                # logging) may already be torn down. Stay silent.
                pass


# --------------------------------------------------------------------------
# Actor-side execution runtime
# --------------------------------------------------------------------------


class ActorExecutionRuntime:
    """Executes actor tasks with per-caller ordering.

    Reference: ``ActorSchedulingQueue`` (in-order by sequence number per
    caller) vs ``OutOfOrderActorSchedulingQueue`` for ``max_concurrency > 1``
    and async actors (``direct_actor_task_submitter.h``, ``fiber.h``).
    """

    def __init__(self, core: CoreWorker, instance: Any, max_concurrency: int = 1):
        self.core = core
        self.instance = instance
        self.max_concurrency = max(1, int(max_concurrency))
        self.is_async = _has_async_methods(instance)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._exec_lock = threading.Lock()  # single-threaded actor body
        # per-caller ordering state: owner addr -> [next expected seq, heap]
        self._order: Dict[Addr, List[Any]] = {}
        if self.is_async:
            import asyncio

            self._loop = asyncio.new_event_loop()
            self._loop_thread = threading.Thread(
                target=self._loop.run_forever, name="actor-asyncio", daemon=True)
            self._loop_thread.start()
        elif self.max_concurrency > 1:
            self._exec_pool = ThreadPoolExecutor(
                max_workers=self.max_concurrency, thread_name_prefix="actor")

    def execute(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        method_name = spec["method"]
        desc = spec.get("desc", method_name)
        try:
            from ray_tpu.util import tracing

            method = getattr(self.instance, method_name)
            args, kwargs = self.core._resolve_args(spec["args_blob"])
            with tracing.activate(spec.get("trace"),
                                  name=f"actor:{method_name}"):
                if self.is_async:
                    result = self._run_async(method, args, kwargs)
                elif self.max_concurrency > 1:
                    # Copy the handler thread's context (incl. the active
                    # trace span) onto the pool thread running user code.
                    import contextvars as _cv

                    ctx = _cv.copy_context()
                    # graftlint: disable=unbounded-blocking-call (the wait IS the actor task: user code owns its duration, and the CALLER'S RpcClient timeout is the bound — a local cap here would kill legitimate long tasks)
                    result = self._exec_pool.submit(
                        lambda: ctx.run(method, *args, **kwargs)).result()
                else:
                    result = self._run_ordered(spec, method, args, kwargs)
            n = len(spec["return_ids"])
            if n == 0:
                results = []
            elif n == 1:
                results = [result]
            else:
                results = list(tuple(result))
                if len(results) != n:
                    raise ValueError(
                        f"actor method {desc} declared num_returns={n} but "
                        f"returned {len(results)} values")
            return {"ok": True, "results": self.core._pack_results(results)}
        except BaseException as e:  # noqa: BLE001
            err = TaskError(e, task_desc=desc)
            return {"ok": False, "error_frame": serialization.serialize(err)}

    def _run_async(self, method, args, kwargs):
        import asyncio
        import inspect

        if inspect.iscoroutinefunction(method):
            from ray_tpu.util import tracing

            trace = tracing.current()  # handler thread's active span

            async def wrapped():
                # The event-loop thread has no trace context; re-enter the
                # caller's span inside the coroutine's own context.
                if trace is None:
                    return await method(*args, **kwargs)
                token = tracing._ctx.set(trace)
                try:
                    return await method(*args, **kwargs)
                finally:
                    tracing._ctx.reset(token)

            fut = asyncio.run_coroutine_threadsafe(wrapped(), self._loop)
            # graftlint: disable=unbounded-blocking-call (same contract as the pool branch: the coroutine IS the actor task and the caller's RPC timeout bounds it end-to-end)
            return fut.result()
        return method(*args, **kwargs)

    def _run_ordered(self, spec, method, args, kwargs):
        """Execute in per-caller submission order (seq numbers).

        Ordering state is keyed by (caller, epoch) — the epoch is the actor
        incarnation the caller believed it was talking to, so a restarted
        actor starts a fresh seq stream per caller. A seq *gap* (an earlier
        call failed before its push, or the caller's epoch view was stale)
        would otherwise wait forever; after ``_GAP_WAIT_S`` the queue gives up
        on the missing seq and proceeds — degraded ordering beats deadlock
        (the reference bounds this differently: failed submissions send
        negative acks to the scheduling queue)."""
        owner = (tuple(spec["owner_addr"]), spec.get("epoch", 0))
        seq = spec.get("seq")
        if seq is None:
            with self._exec_lock:
                return method(*args, **kwargs)
        deadline = time.monotonic() + _GAP_WAIT_S
        with self._cond:
            state = self._order.setdefault(owner, [0, []])
            while state[0] < seq:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    state[0] = seq  # skip the missing seq(s)
                    break
                self._cond.wait(min(remaining, 1.0))
                state = self._order.setdefault(owner, [0, []])
        try:
            with self._exec_lock:
                return method(*args, **kwargs)
        finally:
            with self._cond:
                state = self._order.setdefault(owner, [0, []])
                if seq >= state[0]:
                    state[0] = seq + 1
                self._cond.notify_all()


def _has_async_methods(instance) -> bool:
    import inspect

    for name in dir(instance):
        if name.startswith("__"):
            continue
        try:
            attr = getattr(instance, name)
        except Exception:
            continue
        if inspect.iscoroutinefunction(attr):
            return True
    return False


def _collect_top_level_refs(args: tuple, kwargs: dict) -> List[ObjectRef]:
    refs = [a for a in args if isinstance(a, ObjectRef)]
    refs += [v for v in kwargs.values() if isinstance(v, ObjectRef)]
    return refs
