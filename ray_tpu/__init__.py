"""ray_tpu: a TPU-native distributed AI runtime.

A ground-up rebuild of the capabilities of Ray (tasks / actors / objects,
distributed scheduling with gang placement, distributed training, HPO,
streaming data, serving, RL) designed for JAX/XLA on TPU pods: intra-slice
parallelism (DP/FSDP/TP/SP/EP, ring attention) is expressed as GSPMD sharding
and Pallas kernels compiled to ICI collectives, while this package provides
what XLA does not — the multi-process runtime around the compiled step.

Public core API mirrors the reference's ``ray`` module surface
(``python/ray/__init__.py``): ``init``, ``remote``, ``get``, ``put``,
``wait``, ``kill``, ``get_actor``, plus ``util``-style placement groups.
"""

from ray_tpu._version import version as __version__  # noqa: F401
from ray_tpu.core.api import (  # noqa: F401
    available_resources,
    cluster_resources,
    free,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    remote,
    shutdown,
    wait,
)
from ray_tpu.core.errors import (  # noqa: F401
    ActorDiedError,
    GetTimeoutError,
    ObjectLostError,
    OutOfMemoryError,
    RayTpuError,
    TaskError,
    WorkerCrashedError,
)
from ray_tpu.core.object_ref import ObjectRef  # noqa: F401
from ray_tpu.core.runtime import ObjectRefGenerator  # noqa: F401
from ray_tpu.core.placement import (  # noqa: F401
    NodeAffinitySchedulingStrategy,
    PlacementGroup,
    PlacementGroupSchedulingStrategy,
    SubSliceReservation,
    cluster_topology,
    placement_group,
    remove_placement_group,
    reserve_subslice,
)
from ray_tpu.core.multihost import HostGroup  # noqa: F401
