"""TPU-fused optimizers.

``optax.adamw`` builds its update from several chained transformations
(scale_by_adam -> weight decay -> scale), each a separate tree pass; under
donation-heavy scans XLA doesn't always collapse them, and on
bandwidth-bound chips the optimizer becomes a measurable slice of the step
(452 ms for 711M params on the round-2 bench chip vs ~90 ms of theoretical
HBM traffic). ``adamw`` here emits ONE fused elementwise kernel per leaf —
m, v, and the parameter update computed in a single pass — while keeping
the optax ``GradientTransformation`` interface so it drops into the
existing train-step builder unchanged.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


class FusedAdamWState(NamedTuple):
    count: jax.Array
    mu: optax.Params
    nu: optax.Params


def adamw(learning_rate, b1: float = 0.9, b2: float = 0.999,
          eps: float = 1e-8, weight_decay: float = 0.0,
          mu_dtype=None) -> optax.GradientTransformation:
    """Drop-in fused AdamW (same math as ``optax.adamw``: decoupled weight
    decay applied with the learning rate)."""

    def init(params):
        return FusedAdamWState(
            count=jnp.zeros((), jnp.int32),
            mu=jax.tree.map(
                lambda p: jnp.zeros_like(
                    p, dtype=mu_dtype or p.dtype), params),
            nu=jax.tree.map(lambda p: jnp.zeros_like(p), params),
        )

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("fused adamw requires params")
        count = state.count + 1
        lr = (learning_rate(count) if callable(learning_rate)
              else learning_rate)
        c1 = 1.0 - b1 ** count.astype(jnp.float32)
        c2 = 1.0 - b2 ** count.astype(jnp.float32)

        def leaf(g, m, v, p):
            g = g.astype(v.dtype)
            m2 = b1 * m.astype(v.dtype) + (1.0 - b1) * g
            v2 = b2 * v + (1.0 - b2) * (g * g)
            mhat = m2 / c1
            vhat = v2 / c2
            upd = mhat / (jnp.sqrt(vhat) + eps)
            if weight_decay:
                upd = upd + weight_decay * p.astype(v.dtype)
            return (-lr * upd).astype(p.dtype), m2.astype(m.dtype), v2

        flat = jax.tree.map(leaf, grads, state.mu, state.nu, params)
        updates = jax.tree.map(lambda t: t[0], flat,
                               is_leaf=lambda t: isinstance(t, tuple))
        mu = jax.tree.map(lambda t: t[1], flat,
                          is_leaf=lambda t: isinstance(t, tuple))
        nu = jax.tree.map(lambda t: t[2], flat,
                          is_leaf=lambda t: isinstance(t, tuple))
        return updates, FusedAdamWState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init, update)
