"""Rotary position embeddings (RoPE)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_len: int, theta: float = 10000.0,
                     dtype=jnp.float32):
    """Precompute (cos, sin) tables of shape (max_len, head_dim // 2)."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                           dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array,
               positions: jax.Array = None) -> jax.Array:
    """Rotate pairs of channels. ``x``: (..., seq, heads, head_dim);
    ``cos``/``sin``: (max_len, head_dim//2); ``positions``: (..., seq) offsets
    (defaults to arange, used for decode-time offsets)."""
    seq = x.shape[-3]
    if positions is None:
        cos_t = cos[:seq]
        sin_t = sin[:seq]
        # (seq, hd/2) -> broadcast over heads
        cos_t = cos_t[..., :, None, :]
        sin_t = sin_t[..., :, None, :]
    else:
        cos_t = cos[positions][..., :, None, :]
        sin_t = sin[positions][..., :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate(
        [x1 * cos_t - x2 * sin_t, x2 * cos_t + x1 * sin_t], axis=-1)
    return rotated.astype(x.dtype)
