"""Mixture-of-Experts FFN with expert parallelism (GSPMD dispatch).

SURVEY §2.4's EP row: the reference has no MoE (its role is placement);
the TPU build makes expert parallelism first-class. This is the
GShard/Switch dispatch formulation expressed as einsums so GSPMD lowers
the token->expert exchange to an all-to-all over the ``expert`` mesh axis
(SURVEY §5.8 plane 3 — declared, not hand-written):

    router logits -> top-k gates -> capacity-bounded dispatch mask
    expert_in  (E, C, D)  = dispatch^T tokens      [all-to-all]
    expert_out (E, C, D)  = per-expert FFN (batched matmul, E sharded)
    out        (T, D)     = combine expert_out     [all-to-all back]

Dropped tokens (beyond expert capacity) pass through the residual stream —
standard Switch behavior. Gates are renormalized over the selected top-k.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.parallel.sharding import constrain


def router_topk(
    logits: jax.Array,  # (T, E) fp32
    k: int,
    capacity: int,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k routing with per-expert capacity. Returns
    (dispatch (T, E, C) one-hot, combine (T, E, C) gate weights)."""
    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, expert_idx = jax.lax.top_k(probs, k)  # (T, k)
    if k > 1:
        gate_vals = gate_vals / jnp.maximum(
            gate_vals.sum(-1, keepdims=True), 1e-9)
    # k == 1 keeps the raw softmax prob (Switch Transformer): renormalizing
    # to 1.0 would cut the router out of the gradient path entirely.

    # Position of each (token, choice) in its expert's queue: cumulative
    # count of prior assignments to that expert (priority = token order).
    choice_onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.int32)
    # (T, k, E) -> flatten choices in (token-major, choice-minor) priority.
    flat = choice_onehot.reshape(t * k, e)
    positions = (jnp.cumsum(flat, axis=0) - flat).reshape(t, k, e)
    pos_in_expert = (positions * choice_onehot).sum(-1)  # (T, k)
    keep = pos_in_expert < capacity

    dispatch = (
        jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)[..., None]
        * jax.nn.one_hot(pos_in_expert, capacity, dtype=jnp.float32)[
            :, :, None, :]
        * keep[..., None, None]
    ).sum(1)  # (T, E, C)
    combine = dispatch * gate_vals.sum(1)[:, None, None] if k == 1 else (
        jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)[..., None]
        * jax.nn.one_hot(pos_in_expert, capacity, dtype=jnp.float32)[
            :, :, None, :]
        * (keep * gate_vals)[..., None, None]
    ).sum(1)
    return dispatch, combine


def moe_ffn(
    x: jax.Array,               # (B, S, D)
    params: Dict[str, Any],     # router (D,E); w_gate/w_up (E,D,M); w_down (E,M,D)
    top_k: int = 2,
    capacity_factor: float = 1.25,
) -> Tuple[jax.Array, jax.Array]:
    """MoE feed-forward; returns (output (B,S,D), aux load-balance loss).

    Expert weights carry the ``expert`` logical axis so GSPMD shards the
    per-expert batched matmuls over the expert mesh axis and inserts the
    dispatch/combine all-to-alls.
    """
    b, s, d = x.shape
    e = params["router"].shape[-1]
    t = b * s
    capacity = max(1, int(capacity_factor * top_k * t / e))
    tokens = x.reshape(t, d)

    logits = jnp.einsum("td,de->te", tokens.astype(jnp.float32),
                        params["router"].astype(jnp.float32))
    dispatch, combine = router_topk(logits, top_k, capacity)

    # Switch-style load-balance auxiliary loss.
    probs = jax.nn.softmax(logits, axis=-1)
    frac_tokens = dispatch.sum((0, 2)) / jnp.maximum(dispatch.sum(), 1.0)
    frac_probs = probs.mean(0)
    aux = e * jnp.sum(frac_tokens * frac_probs)

    compute = x.dtype
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(compute),
                           tokens)  # all-to-all (token -> expert shards)
    expert_in = constrain(expert_in, ("expert", None, None))
    gate = jnp.einsum("ecd,edm->ecm", expert_in,
                      params["w_gate"].astype(compute))
    up = jnp.einsum("ecd,edm->ecm", expert_in,
                    params["w_up"].astype(compute))
    act = jax.nn.silu(gate) * up
    expert_out = jnp.einsum("ecm,emd->ecd", act,
                            params["w_down"].astype(compute))
    expert_out = constrain(expert_out, ("expert", None, None))
    out = jnp.einsum("tec,ecd->td", combine.astype(compute), expert_out)
    return out.reshape(b, s, d), aux.astype(jnp.float32)


def init_moe_params(key: jax.Array, dim: int, mlp_dim: int,
                    num_experts: int, dtype=jnp.float32) -> Dict[str, Any]:
    import math

    k1, k2, k3, k4 = jax.random.split(key, 4)

    def normal(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                / math.sqrt(fan_in)).astype(dtype)

    return {
        "router": normal(k1, (dim, num_experts), dim),
        "w_gate": normal(k2, (num_experts, dim, mlp_dim), dim),
        "w_up": normal(k3, (num_experts, dim, mlp_dim), dim),
        "w_down": normal(k4, (num_experts, mlp_dim, dim), mlp_dim),
    }


def moe_param_axes() -> Dict[str, Any]:
    return {
        "router": ("embed", "expert_dim"),
        "w_gate": ("expert", "embed", "mlp"),
        "w_up": ("expert", "embed", "mlp"),
        "w_down": ("expert", "mlp", "embed"),
    }
