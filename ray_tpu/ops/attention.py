"""Attention ops: XLA reference path + chunked (memory-efficient) path.

The TPU replacement for the torch SDPA/flash-attention world the reference's
integrations assume. Two implementations with one signature:

* ``impl="xla"`` — plain einsum softmax; materializes (B,H,Sq,Sk) scores.
  Fastest at short/medium sequence (MXU-bound, XLA fuses mask+softmax).
* ``impl="chunked"`` — online-softmax over KV chunks via ``lax.scan``; never
  materializes the full score matrix. O(S) memory; the building block the
  ring-attention sequence-parallel path reuses per shard
  (``ray_tpu.parallel.ring_attention``).

GQA: ``k``/``v`` may have fewer heads than ``q``; they are repeated to match
(XLA keeps the repeat virtual through the einsum).

Shapes follow (batch, seq, heads, head_dim) throughout the framework.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.repeat(k, n_rep, axis=2)


def attention(
    q: jax.Array,                    # (B, Sq, Hq, D)
    k: jax.Array,                    # (B, Sk, Hkv, D)
    v: jax.Array,                    # (B, Sk, Hkv, D)
    causal: bool = True,
    q_offset: int = 0,               # global position of q[0] (ring/decode)
    kv_offset: int = 0,              # global position of k[0]
    impl: str = "xla",
    chunk_size: int = 512,
    kv_valid: Optional[int] = None,  # keys >= this are masked (tile pad)
) -> jax.Array:
    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    if impl == "xla":
        out, _ = _attention_xla(q, k, v, causal, q_offset, kv_offset,
                                kv_valid)
        return out
    if impl == "chunked":
        if kv_valid is not None:
            raise ValueError("kv_valid is only supported by impl='xla'")
        out, _ = _attention_chunked(q, k, v, causal, q_offset, kv_offset,
                                    chunk_size)
        return out
    raise ValueError(f"unknown attention impl {impl!r}")


def attention_block_stats(q, k, v, causal, q_offset, kv_offset):
    """One attention block returning *unnormalized* accumulator and softmax
    stats: (acc (B,H,Sq,D) fp32, m (B,H,Sq), l (B,H,Sq)). The composable
    unit for ring attention's cross-shard log-sum-exp merge."""
    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    d = q.shape[-1]
    scale = d ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        mask = _mask(q.shape[1], k.shape[1], q_offset, kv_offset)
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    m = jnp.max(scores, axis=-1)
    m_safe = jnp.maximum(m, _NEG_INF / 2)
    p = jnp.exp(scores - m_safe[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return acc, m, l


def merge_attention_stats(acc1, m1, l1, acc2, m2, l2):
    """Log-sum-exp merge of two partial attention results."""
    m = jnp.maximum(m1, m2)
    m_safe = jnp.maximum(m, _NEG_INF / 2)
    c1 = jnp.exp(jnp.maximum(m1, _NEG_INF / 2) - m_safe)
    c2 = jnp.exp(jnp.maximum(m2, _NEG_INF / 2) - m_safe)
    acc = acc1 * c1[..., None] + acc2 * c2[..., None]
    l = l1 * c1 + l2 * c2
    return acc, m, l


def finalize_attention(acc, l, dtype):
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (acc / l_safe[..., None]).transpose(0, 2, 1, 3).astype(dtype)


def _mask(sq: int, sk: int, q_offset, kv_offset) -> jax.Array:
    q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
    k_pos = kv_offset + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
    return q_pos >= k_pos


def _attention_xla(q, k, v, causal, q_offset, kv_offset, kv_valid=None):
    """Returns (out, (max, sumexp)) — the softmax stats make this directly
    composable into ring attention's cross-shard combine."""
    d = q.shape[-1]
    scale = d ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        mask = _mask(q.shape[1], k.shape[1], q_offset, kv_offset)
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    if kv_valid is not None and kv_valid < k.shape[1]:
        # Static tail mask: tile-padding tokens must get zero softmax
        # weight from every real query (exactness of padded shapes).
        alive = jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, 1, k.shape[1]), 3) < kv_valid
        scores = jnp.where(alive, scores, _NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)
    # Fully masked rows (ring attention shards ahead of the causal frontier)
    # must contribute zero, not NaN.
    m_safe = jnp.maximum(m, _NEG_INF / 2)
    p32 = jnp.exp(scores - m_safe)
    l = jnp.sum(p32, axis=-1, keepdims=True)
    # Probabilities stored/multiplied in the compute dtype (bf16): the fp32
    # softmax stats (m, l) are computed above; the (B,H,S,S) probability
    # buffer — the largest temp in the whole training step — lives at half
    # width and the exp fuses into both consumers.
    p = p32.astype(v.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v,
                     preferred_element_type=jnp.float32)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = out / jnp.transpose(l_safe, (0, 2, 1, 3))
    return out.astype(q.dtype), (m.squeeze(-1), l.squeeze(-1))


def _attention_chunked(q, k, v, causal, q_offset, kv_offset, chunk_size):
    """Online-softmax accumulation over KV chunks (lax.scan — static shapes,
    compiler-friendly control flow; no S^2 buffer ever materializes)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    chunk_size = min(chunk_size, sk)
    if sk % chunk_size != 0:
        raise ValueError(f"kv length {sk} not divisible by chunk {chunk_size}")
    n_chunks = sk // chunk_size
    scale = d ** -0.5

    k_chunks = k.reshape(b, n_chunks, chunk_size, h, d).transpose(1, 0, 2, 3, 4)
    v_chunks = v.reshape(b, n_chunks, chunk_size, h, d).transpose(1, 0, 2, 3, 4)

    q32 = q.astype(jnp.float32)

    @jax.checkpoint  # backward recomputes per-chunk probs: O(S*chunk) live
    def step(carry, chunk):
        acc, m, l = carry  # acc: (B,H,Sq,D), m/l: (B,H,Sq)
        idx, k_c, v_c = chunk
        scores = jnp.einsum("bqhd,bkhd->bhqk", q32, k_c.astype(jnp.float32),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            mask = _mask(sq, chunk_size, q_offset,
                         kv_offset + idx * chunk_size)
            scores = jnp.where(mask[None, None], scores, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        m_safe = jnp.maximum(m_new, _NEG_INF / 2)
        p = jnp.exp(scores - m_safe[..., None])
        correction = jnp.exp(jnp.maximum(m, _NEG_INF / 2) - m_safe)
        l_new = l * correction + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bhqd", p, v_c.astype(jnp.float32),
                        preferred_element_type=jnp.float32)
        acc_new = acc * correction[..., None] + pv
        return (acc_new, m_new, l_new), None

    acc0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    (acc, m, l), _ = jax.lax.scan(
        step, (acc0, m0, l0),
        (jnp.arange(n_chunks), k_chunks, v_chunks))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l_safe[..., None]).transpose(0, 2, 1, 3)
    return out.astype(q.dtype), (m, l)
