"""Normalization ops.

RMSNorm as used by the Llama family. Kept as plain jnp: XLA fuses the
reduction + rescale into neighboring ops on TPU (HBM-bandwidth bound, and
fusion is the whole win — a handwritten kernel buys nothing here, which is
exactly the "let XLA fuse" rule from the design notes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm in fp32 accumulation regardless of input dtype (bf16-safe)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    normed = x32 * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(dtype)
