"""Splash attention: schedule-driven block-sparse flash attention (Pallas).

"Splash" = SParse fLASH. Where :mod:`ray_tpu.ops.flash_attention` iterates
the full (q-tile, kv-tile) grid and *skips compute* on dead tiles, this
module builds **per-head static mask schedules** (the defining structure of
the reference-world splash kernel, cf. jax's
``splash_attention_kernel.py``/``splash_attention_mask_info.py`` — studied
for the schedule idea, implemented independently on this repo's kernel
style):

* a :class:`Mask` describes one head's static sparsity (causal, local
  window, chunked/block-diagonal, full);
* heads with different masks are grouped, and for each group the trace-time
  schedule lists, per q-tile, EXACTLY the live kv-tiles —
  ``kv_ids[nq, L]`` + ``lens[nq]`` ride to the kernel as scalar-prefetch
  operands, so the grid's minor axis walks the compacted schedule and dead
  tiles are never even fetched (the flash kernel still pays their
  pipelined loads);
* the backward uses the same schedules (dQ walks the q-schedule, dK/dV the
  TRANSPOSED schedule: per kv-tile, its live q-tiles).

Masking inside a live-but-partial tile is in-register via the mask's
``apply``; fully-live tiles skip it (``full`` flag per schedule slot).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ray_tpu.ops.flash_attention import (
    _LANE,
    _NEG_INF,
    _block_spec,
    _interpret,
    _scratch,
)

try:
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None


# ------------------------------------------------------------------- masks


class Mask:
    """One head's static sparsity pattern. ``live_tile``/``full_tile`` are
    trace-time (numpy scalars); ``apply`` masks scores in-kernel."""

    def live_tile(self, i: int, j: int, bq: int, bk: int) -> bool:
        raise NotImplementedError

    def full_tile(self, i: int, j: int, bq: int, bk: int) -> bool:
        raise NotImplementedError

    def apply(self, s, rows, cols):
        raise NotImplementedError

    def __eq__(self, other):
        return type(self) is type(other) and vars(self) == vars(other)

    def __hash__(self):
        return hash((type(self).__name__, tuple(sorted(vars(self).items()))))


class FullMask(Mask):
    """Dense attention (a "global" head in a mixed-head stack)."""

    def live_tile(self, i, j, bq, bk):
        return True

    def full_tile(self, i, j, bq, bk):
        return True

    def apply(self, s, rows, cols):
        return s


class CausalMask(Mask):
    def live_tile(self, i, j, bq, bk):
        return (i + 1) * bq - 1 >= j * bk

    def full_tile(self, i, j, bq, bk):
        # Entire tile below the diagonal: even the first row sees the last col.
        return i * bq >= (j + 1) * bk - 1

    def apply(self, s, rows, cols):
        return jnp.where(rows >= cols, s, _NEG_INF)


class LocalMask(Mask):
    """Sliding-window attention: causal, keeping the last ``window``
    positions per query (Mistral-style local heads)."""

    def __init__(self, window: int):
        self.window = int(window)

    def live_tile(self, i, j, bq, bk):
        causal_live = (i + 1) * bq - 1 >= j * bk
        win_live = (j + 1) * bk - 1 > i * bq - self.window
        return causal_live and win_live

    def full_tile(self, i, j, bq, bk):
        causal_full = i * bq >= (j + 1) * bk - 1
        # Last row's window still covers the tile's first column.
        win_full = ((i + 1) * bq - 1) - j * bk < self.window
        return causal_full and win_full

    def apply(self, s, rows, cols):
        s = jnp.where(rows >= cols, s, _NEG_INF)
        return jnp.where(rows - cols < self.window, s, _NEG_INF)


class ChunkedMask(Mask):
    """Block-diagonal chunks of ``chunk`` positions (chunked prefill /
    local-global stacks): queries attend causally within their chunk."""

    def __init__(self, chunk: int):
        self.chunk = int(chunk)

    def live_tile(self, i, j, bq, bk):
        if not ((i + 1) * bq - 1 >= j * bk):
            return False
        # Any query row sharing a chunk with any kv col in the tile?
        q_chunks = range(i * bq // self.chunk,
                         ((i + 1) * bq - 1) // self.chunk + 1)
        k_chunks = range(j * bk // self.chunk,
                         ((j + 1) * bk - 1) // self.chunk + 1)
        return bool(set(q_chunks) & set(k_chunks))

    def full_tile(self, i, j, bq, bk):
        same_chunk = (i * bq // self.chunk
                      == ((i + 1) * bq - 1) // self.chunk
                      == j * bk // self.chunk
                      == ((j + 1) * bk - 1) // self.chunk)
        return same_chunk and i * bq >= (j + 1) * bk - 1
    def apply(self, s, rows, cols):
        s = jnp.where(rows >= cols, s, _NEG_INF)
        return jnp.where(rows // self.chunk == cols // self.chunk, s,
                         _NEG_INF)


# --------------------------------------------------------------- schedules


class _Schedule:
    """Compacted per-q-tile kv visit lists for one head group (and the
    transpose for the dK/dV pass)."""

    def __init__(self, mask: Mask, nq: int, nk: int, bq: int, bk: int):
        self.mask = mask
        rows: List[List[int]] = []
        fulls: List[List[int]] = []
        live = np.zeros((nq, nk), bool)
        for i in range(nq):
            js = [j for j in range(nk) if mask.live_tile(i, j, bq, bk)]
            if not js:
                js = [0]  # degenerate row: visit one tile, fully masked
            live[i, [j for j in js]] = True
            rows.append(js)
            fulls.append([int(mask.full_tile(i, j, bq, bk)) for j in js])
        self.q_len = max(len(r) for r in rows)
        self.kv_ids = np.zeros((nq, self.q_len), np.int32)
        self.kv_lens = np.asarray([len(r) for r in rows], np.int32)
        self.kv_full = np.zeros((nq, self.q_len), np.int32)
        for i, (js, fl) in enumerate(zip(rows, fulls)):
            self.kv_ids[i, :len(js)] = js
            self.kv_ids[i, len(js):] = js[-1]  # padding refetches last tile
            self.kv_full[i, :len(fl)] = fl
        # Transpose: per kv-tile, its live q-tiles (dK/dV accumulation).
        cols = [[i for i in range(nq) if live[i, j]] or [0]
                for j in range(nk)]
        self.k_len = max(len(c) for c in cols)
        self.q_ids = np.zeros((nk, self.k_len), np.int32)
        self.q_lens = np.asarray([len(c) for c in cols], np.int32)
        for j, is_ in enumerate(cols):
            self.q_ids[j, :len(is_)] = is_
            self.q_ids[j, len(is_):] = is_[-1]
        self.visited = int(self.kv_lens.sum())
        self.total = nq * nk


def _group_heads(masks: Sequence[Mask]) -> List[Tuple[int, int, Mask]]:
    """Consecutive heads sharing a mask -> (start, count, mask) groups."""
    groups = []
    start = 0
    for h in range(1, len(masks) + 1):
        if h == len(masks) or masks[h] != masks[start]:
            groups.append((start, h - start, masks[start]))
            start = h
    return groups


# ----------------------------------------------------------------- kernels


def _sfwd_kernel(kv_ids, kv_lens, kv_full, *refs, scale, bq, bk, mask):
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
    i = pl.program_id(2)
    t = pl.program_id(3)
    nt = pl.num_programs(3)

    @pl.when(t == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    live = t < kv_lens[i]

    @pl.when(live)
    def _tile():
        j = kv_ids[i, t]
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        # Partial tiles mask in-register; full tiles skip it (the masked
        # value equals s, selected by where on the prefetched flag).
        s = jnp.where(kv_full[i, t] == 1, s, mask.apply(s, rows, cols))
        m_prev = m_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        m_safe = jnp.maximum(m_new, _NEG_INF / 2)
        p = jnp.exp(s - m_safe)
        corr = jnp.exp(jnp.maximum(m_prev, _NEG_INF / 2) - m_safe)
        l_ref[:, 0:1] = l_ref[:, 0:1] * corr + jnp.sum(p, axis=-1,
                                                       keepdims=True)
        m_ref[:, 0:1] = m_new
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * corr + pv

    @pl.when(t == nt - 1)
    def _final():
        l = l_ref[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        lse = jnp.where(l == 0.0, _NEG_INF,
                        jnp.maximum(m_ref[:, 0:1], _NEG_INF / 2)
                        + jnp.log(l_safe))
        lse_ref[0, 0] = jnp.broadcast_to(lse, (bq, _LANE))


def _sched_call(kernel, grid, in_specs, out_specs, out_shape, scratch,
                scalars, args):
    """pallas_call with scalar-prefetch operands (the schedule arrays)."""
    if pltpu is not None:
        spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(scalars), grid=grid,
            in_specs=in_specs, out_specs=out_specs,
            scratch_shapes=scratch)
        return pl.pallas_call(kernel, grid_spec=spec, out_shape=out_shape,
                              interpret=_interpret())(*scalars, *args)
    raise RuntimeError("splash schedules need the pallas TPU frontend")


def _sfwd(q, k, v, schedule: _Schedule, scale, bq, bk):
    b, h, sq, d = q.shape
    nq = sq // bq
    grid = (b, h, nq, schedule.q_len)
    group = h // k.shape[1]

    kernel = functools.partial(_sfwd_kernel, scale=scale, bq=bq, bk=bk,
                               mask=schedule.mask)
    # Index maps see the scalar-prefetch refs after the grid indices; the
    # kv block is looked up FROM THE SCHEDULE — this is the compaction.
    in_specs = [
        _block_spec((1, 1, bq, d),
                    lambda b_, h_, i, t, ids, lens, full: (b_, h_, i, 0)),
        _block_spec((1, 1, bk, d),
                    lambda b_, h_, i, t, ids, lens, full:
                    (b_, h_ // group, ids[i, t], 0)),
        _block_spec((1, 1, bk, d),
                    lambda b_, h_, i, t, ids, lens, full:
                    (b_, h_ // group, ids[i, t], 0)),
    ]
    out_specs = [
        _block_spec((1, 1, bq, d),
                    lambda b_, h_, i, t, ids, lens, full: (b_, h_, i, 0)),
        _block_spec((1, 1, bq, _LANE),
                    lambda b_, h_, i, t, ids, lens, full: (b_, h_, i, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        jax.ShapeDtypeStruct((b, h, sq, _LANE), jnp.float32),
    ]
    scratch = [
        _scratch((bq, d), jnp.float32),
        _scratch((bq, 128), jnp.float32),
        _scratch((bq, 128), jnp.float32),
    ]
    scalars = [jnp.asarray(schedule.kv_ids), jnp.asarray(schedule.kv_lens),
               jnp.asarray(schedule.kv_full)]
    out, lse = _sched_call(kernel, grid, in_specs, out_specs, out_shape,
                           scratch, scalars, [q, k, v])
    return out, lse[..., 0]


def _sbwd_dq_kernel(kv_ids, kv_lens, kv_full, *refs, scale, bq, bk, mask):
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc = refs
    i = pl.program_id(2)
    t = pl.program_id(3)
    nt = pl.num_programs(3)

    @pl.when(t == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    @pl.when(t < kv_lens[i])
    def _tile():
        j = kv_ids[i, t]
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, 0:1]
        delta = delta_ref[0, 0][:, 0:1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(kv_full[i, t] == 1, s, mask.apply(s, rows, cols))
        p = jnp.exp(s - jnp.maximum(lse, _NEG_INF / 2))
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(t == nt - 1)
    def _final():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


def _sbwd_dkv_kernel(q_ids, q_lens, *refs, scale, bq, bk, mask):
    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
     dk_ref, dv_ref, dk_acc, dv_acc) = refs
    j = pl.program_id(2)
    t = pl.program_id(3)
    nt = pl.num_programs(3)

    @pl.when(t == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    @pl.when(t < q_lens[j])
    def _tile():
        i = q_ids[j, t]
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, 0:1]
        delta = delta_ref[0, 0][:, 0:1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = mask.apply(s, rows, cols)
        p = jnp.exp(s - jnp.maximum(lse, _NEG_INF / 2))
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(t == nt - 1)
    def _final():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _sbwd(q, k, v, out, lse, do, schedule: _Schedule, scale, bq, bk):
    b, h, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    group = h // hkv
    nq, nk = sq // bq, sk // bk

    lse_l = jnp.broadcast_to(lse[..., None], (b, h, sq, _LANE))
    delta = jnp.broadcast_to(
        jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                axis=-1, keepdims=True), (b, h, sq, _LANE))

    def lane(index_map):
        return _block_spec((1, 1, bq, _LANE), index_map)

    # dQ over the forward schedule.
    dq_kernel = functools.partial(_sbwd_dq_kernel, scale=scale, bq=bq,
                                  bk=bk, mask=schedule.mask)
    qmap = lambda b_, h_, i, t, ids, lens, full: (b_, h_, i, 0)  # noqa: E731
    kmap = lambda b_, h_, i, t, ids, lens, full: (  # noqa: E731
        b_, h_ // group, ids[i, t], 0)
    dq = _sched_call(
        dq_kernel, (b, h, nq, schedule.q_len),
        [_block_spec((1, 1, bq, d), qmap),
         _block_spec((1, 1, bk, d), kmap),
         _block_spec((1, 1, bk, d), kmap),
         _block_spec((1, 1, bq, d), qmap),
         lane(qmap), lane(qmap)],
        [_block_spec((1, 1, bq, d), qmap)],
        [jax.ShapeDtypeStruct((b, h, sq, d), q.dtype)],
        [_scratch((bq, d), jnp.float32)],
        [jnp.asarray(schedule.kv_ids), jnp.asarray(schedule.kv_lens),
         jnp.asarray(schedule.kv_full)],
        [q, k, v, do, lse_l, delta])[0]

    # dK/dV over the transposed schedule.
    dkv_kernel = functools.partial(_sbwd_dkv_kernel, scale=scale, bq=bq,
                                   bk=bk, mask=schedule.mask)
    qmap2 = lambda b_, h_, j, t, ids, lens: (b_, h_, ids[j, t], 0)  # noqa: E731
    kmap2 = lambda b_, h_, j, t, ids, lens: (b_, h_ // group, j, 0)  # noqa: E731
    dk, dv = _sched_call(
        dkv_kernel, (b, h, nk, schedule.k_len),
        [_block_spec((1, 1, bq, d), qmap2),
         _block_spec((1, 1, bk, d), kmap2),
         _block_spec((1, 1, bk, d), kmap2),
         _block_spec((1, 1, bq, d), qmap2),
         lane(qmap2), lane(qmap2)],
        [_block_spec((1, 1, bk, d),
                     lambda b_, h_, j, t, ids, lens: (b_, h_, j, 0)),
         _block_spec((1, 1, bk, d),
                     lambda b_, h_, j, t, ids, lens: (b_, h_, j, 0))],
        [jax.ShapeDtypeStruct((b, h, sk, d), jnp.float32),
         jax.ShapeDtypeStruct((b, h, sk, d), jnp.float32)],
        [_scratch((bk, d), jnp.float32), _scratch((bk, d), jnp.float32)],
        [jnp.asarray(schedule.q_ids), jnp.asarray(schedule.q_lens)],
        [q, k, v, do, lse_l, delta])
    if group > 1:
        dk = dk.reshape(b, hkv, group, sk, d).sum(axis=2)
        dv = dv.reshape(b, hkv, group, sk, d).sum(axis=2)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# --------------------------------------------------------------- dispatch


def _splash_group(q, k, v, schedule, scale, bq, bk):
    @jax.custom_vjp
    def run(q, k, v):
        return _sfwd(q, k, v, schedule, scale, bq, bk)[0]

    def run_fwd(q, k, v):
        out, lse = _sfwd(q, k, v, schedule, scale, bq, bk)
        return out, (q, k, v, out, lse)

    def run_bwd(res, g):
        q, k, v, out, lse = res
        return _sbwd(q, k, v, out, lse, g, schedule, scale, bq, bk)

    run.defvjp(run_fwd, run_bwd)
    return run(q, k, v)


def splash_attention(
    q: jax.Array,                # (B, S, Hq, D)
    k: jax.Array,                # (B, S, Hkv, D)
    v: jax.Array,                # (B, S, Hkv, D)
    mask: Union[Mask, Sequence[Mask], None] = None,
    causal: bool = True,
    window: Optional[int] = None,
    segment_ids: Optional[jax.Array] = None,
    kv_segment_ids: Optional[jax.Array] = None,
    block_q: int = 256,
    block_k: int = 256,
    scale: Optional[float] = None,
) -> jax.Array:
    """Block-sparse attention with per-head static mask schedules.

    ``mask`` is one :class:`Mask` for all heads or a per-head sequence
    (heads with equal masks share one compacted kernel launch — e.g.
    ``[LocalMask(1024)] * 6 + [FullMask()] * 2`` for a local/global
    stack). With ``mask=None`` the causal/window algebra (and data-
    dependent ``segment_ids``) delegates to the shared flash kernel —
    those patterns gain nothing from explicit schedules that tile
    arithmetic doesn't already give.
    """
    if mask is None:
        from ray_tpu.ops.flash_attention import flash_attention

        return flash_attention(
            q, k, v, causal=causal, window=window, segment_ids=segment_ids,
            kv_segment_ids=kv_segment_ids, block_q=block_q, block_k=block_k,
            scale=scale)
    if segment_ids is not None:
        raise ValueError("segment_ids are data-dependent; use mask=None "
                         "(the flash path) for packed sequences")

    b, sq, hq, d = q.shape
    hkv, sk = k.shape[2], k.shape[1]
    masks = ([mask] * hq if isinstance(mask, Mask) else list(mask))
    if len(masks) != hq:
        raise ValueError(f"{len(masks)} masks for {hq} heads")
    if scale is None:
        scale = d ** -0.5
    bq, bk = min(block_q, sq), min(block_k, sk)
    if sq % bq or sk % bk:
        raise ValueError(f"seq lengths ({sq}, {sk}) must divide blocks "
                         f"({bq}, {bk})")
    group = hq // hkv
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    d_pad = (-d) % _LANE
    if d_pad:
        pad = [(0, 0), (0, 0), (0, 0), (0, d_pad)]
        qt, kt, vt = (jnp.pad(x, pad) for x in (qt, kt, vt))

    outs = []
    for start, count, m in _group_heads(masks):
        if start % group or count % group:
            raise ValueError(
                "per-head masks must align with GQA groups "
                f"(group size {group}); got a boundary at head {start}")
        sched = _Schedule(m, sq // bq, sk // bk, bq, bk)
        outs.append(_splash_group(
            qt[:, start:start + count],
            kt[:, start // group:(start + count) // group],
            vt[:, start // group:(start + count) // group],
            sched, scale, bq, bk))
    out = jnp.concatenate(outs, axis=1) if len(outs) > 1 else outs[0]
    if d_pad:
        out = out[..., :d]
    return out.transpose(0, 2, 1, 3)


def schedule_stats(mask: Mask, seq: int, block_q: int = 256,
                   block_k: int = 256) -> dict:
    """Visited vs total tiles for a mask at a given length — the sparsity
    the schedule actually realizes (observability/tests)."""
    s = _Schedule(mask, seq // block_q, seq // block_k, block_q, block_k)
    return {"visited": s.visited, "total": s.total,
            "density": s.visited / s.total, "q_len": s.q_len}
