"""Splash attention: block-sparse flash attention (TPU Pallas).

SURVEY §5.7 calls for splash-style sparse attention kernels as first-class
citizens of the TPU build. "Splash" = SParse fLASH: the same fused
online-softmax kernel as :mod:`ray_tpu.ops.flash_attention`, but with a
sparsity structure that *skips whole tiles*:

* ``causal`` — lower-triangular band; upper tiles never compute.
* ``window`` — sliding-window/local attention; tiles outside the last
  ``window`` positions per query are skipped, so cost is O(S * window)
  rather than O(S^2). This is the long-context workhorse (Mistral-style
  local layers, chunked prefill).
* ``segment_ids`` — packed-sequence masking: queries only attend within
  their own segment (data-dependent, masked in-register).

All three compose, and the fused backward applies the identical structure,
so the speedup carries to training. Implemented on the shared kernel in
``flash_attention.py`` (tile-skip arithmetic: ``_tile_live``); this module
is the named public surface.
"""

from __future__ import annotations

from typing import Optional

import jax

from ray_tpu.ops.flash_attention import flash_attention


def splash_attention(
    q: jax.Array,                # (B, S, Hq, D)
    k: jax.Array,                # (B, S, Hkv, D)
    v: jax.Array,                # (B, S, Hkv, D)
    causal: bool = True,
    window: Optional[int] = None,
    segment_ids: Optional[jax.Array] = None,
    kv_segment_ids: Optional[jax.Array] = None,
    block_q: int = 256,
    block_k: int = 256,
    scale: Optional[float] = None,
) -> jax.Array:
    """Block-sparse attention; see module docstring for the mask algebra."""
    return flash_attention(
        q, k, v, causal=causal, window=window, segment_ids=segment_ids,
        kv_segment_ids=kv_segment_ids, block_q=block_q, block_k=block_k,
        scale=scale)
