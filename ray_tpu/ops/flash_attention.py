"""Pallas TPU flash/splash attention: fused, tiled, O(S) memory, custom VJP.

The TPU-native replacement for the flash/splash attention kernels the
reference world gets from CUDA libraries (its integrations defer to torch
SDPA; SURVEY §5.7 requires the TPU build to make these kernels first-class).
Design, per the Pallas TPU playbook:

* Layout (B, H, S, D): the (S, D) minor tile maps q/k/v blocks straight onto
  (sublane, lane) tiling; D is padded to a lane multiple (128) when needed.
* Forward: online-softmax over KV tiles with fp32 accumulators in VMEM
  scratch; emits the log-sum-exp alongside the output so the backward can
  recompute probabilities without ever materializing the (S, S) score
  matrix.
* Backward: two kernels with flash-attention-2 style recomputation — one
  accumulates dK/dV (grid minor axis = query tiles), one accumulates dQ
  (grid minor axis = KV tiles). ``delta = rowsum(dO * O)`` is a cheap
  elementwise pass left to XLA.
* SPLASH-style block sparsity: causal masking, a sliding ``window``, and
  ``segment_ids`` compose. Causal/window masks skip fully-dead tiles with
  ``pl.when`` by tile arithmetic (no compute, only the pipelined fetch), so
  local attention costs O(S * window) not O(S^2); partial tiles and segment
  boundaries mask in-register. ``q_offset`` shifts the causal/window
  frontier so ring attention / decode reuse the same kernel per shard.
* GQA: the KV head for a query head is selected in the BlockSpec index map
  (``h // group``) — the repeat never materializes.
* ``flash_attention_stats`` returns (out, lse) with a VJP that accepts a
  cotangent for lse (``ds += p * g_lse``) — the hook ring attention's
  cross-shard online-softmax merge differentiates through.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend (absent on pure-CPU builds)
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_NEG_INF = -1e30
_LANE = 128  # TPU lane width: minor dim of every block must divide into it


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _block_spec(block_shape, index_map):
    if _VMEM is None:
        return pl.BlockSpec(block_shape, index_map)
    return pl.BlockSpec(block_shape, index_map, memory_space=_VMEM)


def _scratch(shape, dtype):
    if pltpu is None:
        return pl.MemoryRef(shape, dtype) if hasattr(pl, "MemoryRef") else None
    return pltpu.VMEM(shape, dtype)


# ------------------------------------------------------------------ masks


def _tile_live(i, j, block_q, block_k, q_offset, causal, window):
    """Is any (row, col) of tile (i, j) unmasked by the causal/window
    bands? Segment masks are data-dependent and never skip tiles."""
    row_min = q_offset + i * block_q
    row_max = row_min + block_q - 1
    col_min = j * block_k
    col_max = col_min + block_k - 1
    live = True
    if causal:
        live = jnp.logical_and(live, row_max >= col_min)
    if window is not None:
        # Sliding window keeps cols in (row - window, row].
        live = jnp.logical_and(live, col_max > row_min - window)
    return live


def _mask_scores(s, i, j, block_q, block_k, q_offset, causal, window,
                 seg_q=None, seg_k=None):
    if not causal and window is None and seg_q is None:
        return s
    rows = q_offset + i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    if causal:
        s = jnp.where(rows >= cols, s, _NEG_INF)
    if window is not None:
        s = jnp.where(rows - cols < window, s, _NEG_INF)
    if seg_q is not None:
        # seg ids ride as fp32 rows (exact for ids < 2^24); equality only.
        s = jnp.where(seg_q.reshape(block_q, 1) == seg_k.reshape(1, block_k),
                      s, _NEG_INF)
    return s


# ---------------------------------------------------------------- forward


def _fwd_kernel(*refs, scale, block_q, block_k, causal, window, q_offset,
                segmented):
    if segmented:
        (q_ref, k_ref, v_ref, sq_ref, sk_ref,
         o_ref, lse_ref, acc_ref, m_ref, l_ref) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
        sq_ref = sk_ref = None
    i = pl.program_id(2)  # query tile
    j = pl.program_id(3)  # kv tile
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    live = _tile_live(i, j, block_q, block_k, q_offset, causal, window)

    @pl.when(live)
    def _tile():
        q = q_ref[0, 0]  # (block_q, D)
        k = k_ref[0, 0]  # (block_k, D)
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = _mask_scores(
            s, i, j, block_q, block_k, q_offset, causal, window,
            None if sq_ref is None else sq_ref[0],
            None if sk_ref is None else sk_ref[0])
        m_prev = m_ref[:, 0:1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        m_safe = jnp.maximum(m_new, _NEG_INF / 2)
        p = jnp.exp(s - m_safe)
        corr = jnp.exp(jnp.maximum(m_prev, _NEG_INF / 2) - m_safe)
        l_ref[:, 0:1] = l_ref[:, 0:1] * corr + jnp.sum(p, axis=-1,
                                                       keepdims=True)
        m_ref[:, 0:1] = m_new
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * corr + pv

    @pl.when(j == nk - 1)
    def _final():
        l = l_ref[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        m = m_ref[:, 0:1]
        lse = jnp.where(
            l == 0.0, _NEG_INF,
            jnp.maximum(m, _NEG_INF / 2) + jnp.log(l_safe))
        # TPU blocks need a 128-lane minor dim: lse is broadcast across the
        # lane axis (same trick as jax's in-tree kernel); readers use lane 0.
        lse_ref[0, 0] = jnp.broadcast_to(lse, (lse.shape[0], _LANE))


def _fwd(q, k, v, seg_q, seg_k, scale, causal, window, q_offset,
         block_q, block_k):
    b, h, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    group = h // hkv
    nq, nk = sq // block_q, sk // block_k
    grid = (b, h, nq, nk)
    segmented = seg_q is not None

    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, window=window, q_offset=q_offset, segmented=segmented)
    in_specs = [
        _block_spec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
        _block_spec((1, 1, block_k, d),
                    lambda b_, h_, i, j: (b_, h_ // group, j, 0)),
        _block_spec((1, 1, block_k, d),
                    lambda b_, h_, i, j: (b_, h_ // group, j, 0)),
    ]
    args = [q, k, v]
    if segmented:
        in_specs += [
            _block_spec((1, block_q), lambda b_, h_, i, j: (b_, i)),
            _block_spec((1, block_k), lambda b_, h_, i, j: (b_, j)),
        ]
        args += [seg_q, seg_k]
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            _block_spec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            _block_spec((1, 1, block_q, _LANE),
                        lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, _LANE), jnp.float32),
        ],
        scratch_shapes=[
            _scratch((block_q, d), jnp.float32),
            _scratch((block_q, 128), jnp.float32),
            _scratch((block_q, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    # Keep only lane 0 (the value; other lanes are the tiling broadcast) so
    # the residual saved for the backward is (B, H, S), not 128x that.
    return out, lse[..., 0]


# --------------------------------------------------------------- backward


def _bwd_dkv_kernel(*refs, scale, block_q, block_k, causal, window, q_offset,
                    segmented, has_dlse):
    it = iter(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = (
        next(it), next(it), next(it), next(it), next(it), next(it))
    dlse_ref = next(it) if has_dlse else None
    sq_ref = next(it) if segmented else None
    sk_ref = next(it) if segmented else None
    dk_ref, dv_ref, dk_acc, dv_acc = next(it), next(it), next(it), next(it)
    i = pl.program_id(3)  # query tile (minor)
    j = pl.program_id(2)  # kv tile
    ni = pl.num_programs(3)

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    live = _tile_live(i, j, block_q, block_k, q_offset, causal, window)

    @pl.when(live)
    def _tile():
        q = q_ref[0, 0]          # (bq, D)
        k = k_ref[0, 0]          # (bk, D)
        v = v_ref[0, 0]
        do = do_ref[0, 0]        # (bq, D)
        lse = lse_ref[0, 0][:, 0:1]      # (bq, 1); lane-0 of padded layout
        delta = delta_ref[0, 0][:, 0:1]  # (bq, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = _mask_scores(
            s, i, j, block_q, block_k, q_offset, causal, window,
            None if sq_ref is None else sq_ref[0],
            None if sk_ref is None else sk_ref[0])
        p = jnp.exp(s - jnp.maximum(lse, _NEG_INF / 2))  # (bq, bk)
        # dV += P^T dO
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dP = dO V^T ; dS = P * (dP - delta [+ g_lse]) * scale
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dlse_ref is not None:
            dp = dp + dlse_ref[0, 0][:, 0:1]
        ds = p * (dp - delta) * scale
        # dK += dS^T Q
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == ni - 1)
    def _final():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(*refs, scale, block_q, block_k, causal, window, q_offset,
                   segmented, has_dlse):
    it = iter(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = (
        next(it), next(it), next(it), next(it), next(it), next(it))
    dlse_ref = next(it) if has_dlse else None
    sq_ref = next(it) if segmented else None
    sk_ref = next(it) if segmented else None
    dq_ref, dq_acc = next(it), next(it)
    i = pl.program_id(2)  # query tile
    j = pl.program_id(3)  # kv tile (minor)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    live = _tile_live(i, j, block_q, block_k, q_offset, causal, window)

    @pl.when(live)
    def _tile():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, 0:1]
        delta = delta_ref[0, 0][:, 0:1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = _mask_scores(
            s, i, j, block_q, block_k, q_offset, causal, window,
            None if sq_ref is None else sq_ref[0],
            None if sk_ref is None else sk_ref[0])
        p = jnp.exp(s - jnp.maximum(lse, _NEG_INF / 2))
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dlse_ref is not None:
            dp = dp + dlse_ref[0, 0][:, 0:1]
        ds = (p * (dp - delta) * scale)
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _final():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd(q, k, v, seg_q, seg_k, out, lse, do, dlse, scale, causal, window,
         q_offset, block_q, block_k):
    b, h, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    group = h // hkv
    nq, nk = sq // block_q, sk // block_k
    segmented = seg_q is not None
    has_dlse = dlse is not None

    # (B, H, S, LANE): lse and delta broadcast across the lane axis so their
    # blocks are TPU-tileable (kernels read lane 0).
    lse = jnp.broadcast_to(lse[..., None], (b, h, sq, _LANE))
    delta = jnp.broadcast_to(
        jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                axis=-1, keepdims=True),
        (b, h, sq, _LANE))
    extra = []
    if has_dlse:
        extra.append(jnp.broadcast_to(
            dlse.astype(jnp.float32)[..., None], (b, h, sq, _LANE)))
    if segmented:
        extra += [seg_q, seg_k]

    def lane_spec(index_map):
        return _block_spec((1, 1, block_q, _LANE), index_map)

    # dK/dV: one (b, kv-head, kv-tile) program accumulates over all query
    # tiles of every query head in the group (GQA reduction folded into the
    # grid's minor axis).
    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, window=window, q_offset=q_offset,
        segmented=segmented, has_dlse=has_dlse)
    grid_dkv = (b, h, nk, nq)
    qmap = lambda b_, h_, j, i: (b_, h_, i, 0)        # noqa: E731
    kmap = lambda b_, h_, j, i: (b_, h_ // group, j, 0)  # noqa: E731
    in_specs = [
        _block_spec((1, 1, block_q, d), qmap),
        _block_spec((1, 1, block_k, d), kmap),
        _block_spec((1, 1, block_k, d), kmap),
        _block_spec((1, 1, block_q, d), qmap),
        lane_spec(qmap),
        lane_spec(qmap),
    ]
    if has_dlse:
        in_specs.append(lane_spec(qmap))
    if segmented:
        in_specs += [
            _block_spec((1, block_q), lambda b_, h_, j, i: (b_, i)),
            _block_spec((1, block_k), lambda b_, h_, j, i: (b_, j)),
        ]
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=grid_dkv,
        in_specs=in_specs,
        out_specs=[
            _block_spec((1, 1, block_k, d),
                        lambda b_, h_, j, i: (b_, h_, j, 0)),
            _block_spec((1, 1, block_k, d),
                        lambda b_, h_, j, i: (b_, h_, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, sk, d), jnp.float32),
        ],
        scratch_shapes=[
            _scratch((block_k, d), jnp.float32),
            _scratch((block_k, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta, *extra)
    if group > 1:
        dk = dk.reshape(b, hkv, group, sk, d).sum(axis=2)
        dv = dv.reshape(b, hkv, group, sk, d).sum(axis=2)
    dk = dk.astype(k.dtype)
    dv = dv.astype(v.dtype)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, window=window, q_offset=q_offset,
        segmented=segmented, has_dlse=has_dlse)
    grid_dq = (b, h, nq, nk)
    qmap2 = lambda b_, h_, i, j: (b_, h_, i, 0)          # noqa: E731
    kmap2 = lambda b_, h_, i, j: (b_, h_ // group, j, 0)  # noqa: E731
    in_specs = [
        _block_spec((1, 1, block_q, d), qmap2),
        _block_spec((1, 1, block_k, d), kmap2),
        _block_spec((1, 1, block_k, d), kmap2),
        _block_spec((1, 1, block_q, d), qmap2),
        lane_spec(qmap2),
        lane_spec(qmap2),
    ]
    if has_dlse:
        in_specs.append(lane_spec(qmap2))
    if segmented:
        in_specs += [
            _block_spec((1, block_q), lambda b_, h_, i, j: (b_, i)),
            _block_spec((1, block_k), lambda b_, h_, i, j: (b_, j)),
        ]
    dq = pl.pallas_call(
        dq_kernel,
        grid=grid_dq,
        in_specs=in_specs,
        out_specs=[
            _block_spec((1, 1, block_q, d), qmap2),
        ],
        out_shape=[jax.ShapeDtypeStruct((b, h, sq, d), q.dtype)],
        scratch_shapes=[_scratch((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta, *extra)[0]
    return dq, dk, dv


# ------------------------------------------------------------- custom VJPs


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash(q, k, v, seg_q, seg_k, scale, causal, window, q_offset,
           block_q, block_k):
    out, _ = _fwd(q, k, v, seg_q, seg_k, scale, causal, window, q_offset,
                  block_q, block_k)
    return out


def _flash_fwd(q, k, v, seg_q, seg_k, scale, causal, window, q_offset,
               block_q, block_k):
    out, lse = _fwd(q, k, v, seg_q, seg_k, scale, causal, window, q_offset,
                    block_q, block_k)
    return out, (q, k, v, seg_q, seg_k, out, lse)


def _flash_bwd(scale, causal, window, q_offset, block_q, block_k, res, g):
    q, k, v, seg_q, seg_k, out, lse = res
    dq, dk, dv = _bwd(q, k, v, seg_q, seg_k, out, lse, g, None, scale,
                      causal, window, q_offset, block_q, block_k)
    zseg = (None if seg_q is None else jnp.zeros_like(seg_q),
            None if seg_k is None else jnp.zeros_like(seg_k))
    return (dq, dk, dv) + zseg


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention_stats(q, k, v, scale, causal, window, q_offset,
                          block_q, block_k) -> Tuple[jax.Array, jax.Array]:
    """(out, lse) with a VJP accepting cotangents for both. Shapes are
    (B, H, S, D) / (B, H, S); used by ring attention's cross-shard merge."""

    @functools.partial(jax.custom_vjp, nondiff_argnums=())
    def stats(q, k, v):
        return _fwd(q, k, v, None, None, scale, causal, window, q_offset,
                    block_q, block_k)

    def stats_fwd(q, k, v):
        out, lse = _fwd(q, k, v, None, None, scale, causal, window, q_offset,
                        block_q, block_k)
        return (out, lse), (q, k, v, out, lse)

    def stats_bwd(res, cotangents):
        g, g_lse = cotangents
        q, k, v, out, lse = res
        dq, dk, dv = _bwd(q, k, v, None, None, out, lse, g, g_lse, scale,
                          causal, window, q_offset, block_q, block_k)
        return dq, dk, dv

    stats.defvjp(stats_fwd, stats_bwd)
    return stats(q, k, v)


# ------------------------------------------------------------- public API


def flash_attention(
    q: jax.Array,                # (B, S, Hq, D)
    k: jax.Array,                # (B, S, Hkv, D)
    v: jax.Array,                # (B, S, Hkv, D)
    causal: bool = True,
    q_offset: int = 0,
    block_q: int = 256,
    block_k: int = 256,
    scale: Optional[float] = None,
    window: Optional[int] = None,
    segment_ids: Optional[jax.Array] = None,      # (B, S) int
    kv_segment_ids: Optional[jax.Array] = None,   # (B, S_kv) int
) -> jax.Array:
    """Flash attention over (batch, seq, heads, head_dim) tensors.

    Drop-in for ``ray_tpu.ops.attention.attention`` (same signature shape);
    differentiable via the fused Pallas backward. ``window`` keeps only the
    last ``window`` positions per query (sliding-window/local attention —
    dead tiles are skipped, so cost is O(S*window)); ``segment_ids`` masks
    cross-segment attention (packed sequences), splash-style.
    """
    b, sq, hq, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    if scale is None:
        scale = d ** -0.5

    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"seq lengths ({sq}, {sk}) must divide blocks ({block_q}, "
            f"{block_k})")

    # (B, S, H, D) -> (B, H, S, D): puts (S, D) on the (sublane, lane) tile.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    # Lane-align head_dim (zero-pad is exact: scores unchanged, padded
    # output columns are sliced off).
    d_pad = (-d) % 128
    if d_pad:
        pad = [(0, 0), (0, 0), (0, 0), (0, d_pad)]
        qt = jnp.pad(qt, pad)
        kt = jnp.pad(kt, pad)
        vt = jnp.pad(vt, pad)

    seg_q = seg_k = None
    if kv_segment_ids is not None and segment_ids is None:
        raise ValueError(
            "kv_segment_ids requires segment_ids (the query-side ids); "
            "pass both to mask packed cross-attention")
    if segment_ids is not None:
        # fp32 ids: exact equality for ids < 2^24, and the cotangent space
        # stays float (custom_vjp needs a concrete zero to return).
        seg_q = segment_ids.astype(jnp.float32)
        seg_k = (segment_ids if kv_segment_ids is None
                 else kv_segment_ids).astype(jnp.float32)

    out = _flash(qt, kt, vt, seg_q, seg_k, scale, causal, window, q_offset,
                 block_q, block_k)
    if d_pad:
        out = out[..., :d]
    return out.transpose(0, 2, 1, 3)
