"""Pallas TPU flash attention: fused, tiled, O(S) memory, custom VJP.

The TPU-native replacement for the flash/splash attention kernels the
reference world gets from CUDA libraries (its integrations defer to torch
SDPA; SURVEY §5.7 requires the TPU build to make these kernels first-class).
Design, per the Pallas TPU playbook:

* Layout (B, H, S, D): the (S, D) minor tile maps q/k/v blocks straight onto
  (sublane, lane) tiling; D is padded to a lane multiple (128) when needed.
* Forward: online-softmax over KV tiles with fp32 accumulators in VMEM
  scratch; emits the log-sum-exp alongside the output so the backward can
  recompute probabilities without ever materializing the (S, S) score
  matrix.
* Backward: two kernels with flash-attention-2 style recomputation — one
  accumulates dK/dV (grid minor axis = query tiles), one accumulates dQ
  (grid minor axis = KV tiles). ``delta = rowsum(dO * O)`` is a cheap
  elementwise pass left to XLA.
* Causal masking by tile arithmetic: fully-masked tiles are skipped with
  ``pl.when`` (no compute, only the pipelined fetch), partial tiles mask
  in-register. ``q_offset`` shifts the causal frontier so ring attention /
  decode reuse the same kernel per shard.
* GQA: the KV head for a query head is selected in the BlockSpec index map
  (``h // group``) — the repeat never materializes.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend (absent on pure-CPU builds)
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_NEG_INF = -1e30
_LANE = 128  # TPU lane width: minor dim of every block must divide into it


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _block_spec(block_shape, index_map):
    if _VMEM is None:
        return pl.BlockSpec(block_shape, index_map)
    return pl.BlockSpec(block_shape, index_map, memory_space=_VMEM)


def _scratch(shape, dtype):
    if pltpu is None:
        return pl.MemoryRef(shape, dtype) if hasattr(pl, "MemoryRef") else None
    return pltpu.VMEM(shape, dtype)


# ---------------------------------------------------------------- forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, block_q, block_k, causal, q_offset):
    i = pl.program_id(2)  # query tile
    j = pl.program_id(3)  # kv tile
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Tile-level causal skip: tile is live unless its every (row, col) has
    # row < col. Rows start at q_offset + i*block_q, cols at j*block_k.
    row_max = q_offset + i * block_q + block_q - 1
    col_min = j * block_k
    live = jnp.logical_or(not causal, row_max >= col_min)

    @pl.when(live)
    def _tile():
        q = q_ref[0, 0]  # (block_q, D)
        k = k_ref[0, 0]  # (block_k, D)
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = q_offset + i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_prev = m_ref[:, 0:1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        m_safe = jnp.maximum(m_new, _NEG_INF / 2)
        p = jnp.exp(s - m_safe)
        corr = jnp.exp(jnp.maximum(m_prev, _NEG_INF / 2) - m_safe)
        l_ref[:, 0:1] = l_ref[:, 0:1] * corr + jnp.sum(p, axis=-1,
                                                       keepdims=True)
        m_ref[:, 0:1] = m_new
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * corr + pv

    @pl.when(j == nk - 1)
    def _final():
        l = l_ref[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        m = m_ref[:, 0:1]
        lse = jnp.where(
            l == 0.0, _NEG_INF,
            jnp.maximum(m, _NEG_INF / 2) + jnp.log(l_safe))
        # TPU blocks need a 128-lane minor dim: lse is broadcast across the
        # lane axis (same trick as jax's in-tree kernel); readers use lane 0.
        lse_ref[0, 0] = jnp.broadcast_to(lse, (lse.shape[0], _LANE))


def _fwd(q, k, v, scale, causal, q_offset, block_q, block_k):
    b, h, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    group = h // hkv
    nq, nk = sq // block_q, sk // block_k
    grid = (b, h, nq, nk)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, q_offset=q_offset)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _block_spec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            _block_spec((1, 1, block_k, d),
                        lambda b_, h_, i, j: (b_, h_ // group, j, 0)),
            _block_spec((1, 1, block_k, d),
                        lambda b_, h_, i, j: (b_, h_ // group, j, 0)),
        ],
        out_specs=[
            _block_spec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            _block_spec((1, 1, block_q, _LANE),
                        lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, _LANE), jnp.float32),
        ],
        scratch_shapes=[
            _scratch((block_q, d), jnp.float32),
            _scratch((block_q, 128), jnp.float32),
            _scratch((block_q, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    # Keep only lane 0 (the value; other lanes are the tiling broadcast) so
    # the residual saved for the backward is (B, H, S), not 128x that.
    return out, lse[..., 0]


# --------------------------------------------------------------- backward


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, scale, block_q, block_k, causal, q_offset):
    i = pl.program_id(3)  # query tile (minor)
    j = pl.program_id(2)  # kv tile
    ni = pl.num_programs(3)

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    row_max = q_offset + i * block_q + block_q - 1
    col_min = j * block_k
    live = jnp.logical_or(not causal, row_max >= col_min)

    @pl.when(live)
    def _tile():
        q = q_ref[0, 0]          # (bq, D)
        k = k_ref[0, 0]          # (bk, D)
        v = v_ref[0, 0]
        do = do_ref[0, 0]        # (bq, D)
        lse = lse_ref[0, 0][:, 0:1]      # (bq, 1); lane-0 of padded layout
        delta = delta_ref[0, 0][:, 0:1]  # (bq, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = q_offset + i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - jnp.maximum(lse, _NEG_INF / 2))  # (bq, bk)
        # dV += P^T dO
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dP = dO V^T ; dS = P * (dP - delta) * scale
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        # dK += dS^T Q
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == ni - 1)
    def _final():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc,
                   *, scale, block_q, block_k, causal, q_offset):
    i = pl.program_id(2)  # query tile
    j = pl.program_id(3)  # kv tile (minor)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    row_max = q_offset + i * block_q + block_q - 1
    col_min = j * block_k
    live = jnp.logical_or(not causal, row_max >= col_min)

    @pl.when(live)
    def _tile():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, 0:1]
        delta = delta_ref[0, 0][:, 0:1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = q_offset + i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - jnp.maximum(lse, _NEG_INF / 2))
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale)
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _final():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd(q, k, v, out, lse, do, scale, causal, q_offset, block_q, block_k):
    b, h, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    group = h // hkv
    nq, nk = sq // block_q, sk // block_k

    # (B, H, S, LANE): lse and delta broadcast across the lane axis so their
    # blocks are TPU-tileable (kernels read lane 0).
    lse = jnp.broadcast_to(lse[..., None], (b, h, sq, _LANE))
    delta = jnp.broadcast_to(
        jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                axis=-1, keepdims=True),
        (b, h, sq, _LANE))

    # dK/dV: one (b, kv-head, kv-tile) program accumulates over all query
    # tiles of every query head in the group (GQA reduction folded into the
    # grid's minor axis).
    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, q_offset=q_offset)
    grid_dkv = (b, h, nk, nq)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=grid_dkv,
        in_specs=[
            _block_spec((1, 1, block_q, d),
                        lambda b_, h_, j, i: (b_, h_, i, 0)),
            _block_spec((1, 1, block_k, d),
                        lambda b_, h_, j, i: (b_, h_ // group, j, 0)),
            _block_spec((1, 1, block_k, d),
                        lambda b_, h_, j, i: (b_, h_ // group, j, 0)),
            _block_spec((1, 1, block_q, d),
                        lambda b_, h_, j, i: (b_, h_, i, 0)),
            _block_spec((1, 1, block_q, _LANE),
                        lambda b_, h_, j, i: (b_, h_, i, 0)),
            _block_spec((1, 1, block_q, _LANE),
                        lambda b_, h_, j, i: (b_, h_, i, 0)),
        ],
        out_specs=[
            _block_spec((1, 1, block_k, d),
                        lambda b_, h_, j, i: (b_, h_, j, 0)),
            _block_spec((1, 1, block_k, d),
                        lambda b_, h_, j, i: (b_, h_, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, sk, d), jnp.float32),
        ],
        scratch_shapes=[
            _scratch((block_k, d), jnp.float32),
            _scratch((block_k, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    if group > 1:
        dk = dk.reshape(b, hkv, group, sk, d).sum(axis=2)
        dv = dv.reshape(b, hkv, group, sk, d).sum(axis=2)
    dk = dk.astype(k.dtype)
    dv = dv.astype(v.dtype)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, q_offset=q_offset)
    grid_dq = (b, h, nq, nk)
    dq = pl.pallas_call(
        dq_kernel,
        grid=grid_dq,
        in_specs=[
            _block_spec((1, 1, block_q, d),
                        lambda b_, h_, i, j: (b_, h_, i, 0)),
            _block_spec((1, 1, block_k, d),
                        lambda b_, h_, i, j: (b_, h_ // group, j, 0)),
            _block_spec((1, 1, block_k, d),
                        lambda b_, h_, i, j: (b_, h_ // group, j, 0)),
            _block_spec((1, 1, block_q, d),
                        lambda b_, h_, i, j: (b_, h_, i, 0)),
            _block_spec((1, 1, block_q, _LANE),
                        lambda b_, h_, i, j: (b_, h_, i, 0)),
            _block_spec((1, 1, block_q, _LANE),
                        lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_specs=[
            _block_spec((1, 1, block_q, d),
                        lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((b, h, sq, d), q.dtype)],
        scratch_shapes=[_scratch((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)[0]
    return dq, dk, dv


# ------------------------------------------------------------- public API


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, q_offset, block_q, block_k):
    out, _ = _fwd(q, k, v, scale, causal, q_offset, block_q, block_k)
    return out


def _flash_fwd(q, k, v, scale, causal, q_offset, block_q, block_k):
    out, lse = _fwd(q, k, v, scale, causal, q_offset, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, q_offset, block_q, block_k, res, g):
    q, k, v, out, lse = res
    dq, dk, dv = _bwd(q, k, v, out, lse, g, scale, causal, q_offset,
                      block_q, block_k)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,                # (B, S, Hq, D)
    k: jax.Array,                # (B, S, Hkv, D)
    v: jax.Array,                # (B, S, Hkv, D)
    causal: bool = True,
    q_offset: int = 0,
    block_q: int = 256,
    block_k: int = 256,
    scale: Optional[float] = None,
) -> jax.Array:
    """Flash attention over (batch, seq, heads, head_dim) tensors.

    Drop-in for ``ray_tpu.ops.attention.attention`` (same signature shape);
    differentiable via the fused Pallas backward.
    """
    b, sq, hq, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    if scale is None:
        scale = d ** -0.5

    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"seq lengths ({sq}, {sk}) must divide blocks ({block_q}, "
            f"{block_k})")

    # (B, S, H, D) -> (B, H, S, D): puts (S, D) on the (sublane, lane) tile.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    # Lane-align head_dim (zero-pad is exact: scores unchanged, padded
    # output columns are sliced off).
    d_pad = (-d) % 128
    if d_pad:
        pad = [(0, 0), (0, 0), (0, 0), (0, d_pad)]
        qt = jnp.pad(qt, pad)
        kt = jnp.pad(kt, pad)
        vt = jnp.pad(vt, pad)

    out = _flash(qt, kt, vt, scale, causal, q_offset, block_q, block_k)
    if d_pad:
        out = out[..., :d]
    return out.transpose(0, 2, 1, 3)
