"""TPU detection and pod-slice topology as first-class scheduler resources.

Analogue of the reference's ``python/ray/_private/accelerators/tpu.py``
(``TPUAcceleratorManager`` :71 — chip detection :274, pod topology :198, GCE
metadata polling :49, and the ``TPU-{pod_type}-head`` gang resource :381).
Detection here is JAX-native — ask the runtime what is attached — with env
metadata as fallback, and the gang primitive is a real placement group over
per-host bundles rather than a synthetic head resource.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

# chips per TPU-VM host for common generations (v4/v5p: 4 chips/host;
# v5e/v6e: up to 8 chips/host depending on slice shape).
_CHIPS_PER_HOST_DEFAULT = 4

_PEAK_BF16_FLOPS = {
    # per-chip peak bf16 matmul FLOP/s (public spec sheets)
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v5": 197e12,       # "TPU v5 lite" device kind
    "v6e": 918e12,
}


def detect_chip_count(timeout_s: float = 20.0) -> Tuple[int, Optional[str]]:
    """Return (local chip count, pod type) without initializing distributed
    JAX. Returns (0, None) when no TPU is attached.

    Detection probes in a SUBPROCESS under a timeout: backend discovery
    talks to the accelerator plumbing (driver/tunnel), and a wedged
    transport would otherwise hang ``ray_tpu.init`` forever — worse, an
    in-process probe thread that hangs POISONS jax's process-wide
    backend-init lock, so every later jax call in the driver would hang
    too. A killed subprocess leaves this process's jax untouched and the
    cluster comes up CPU-only (reference analogue: accelerator managers
    shell out to nvidia-smi / GCE metadata with timeouts)."""
    import subprocess
    import sys

    pod_type = os.environ.get("TPU_ACCELERATOR_TYPE")  # e.g. "v5e-16"
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return 0, pod_type  # explicitly CPU-pinned: nothing to probe
    probe_src = (
        "import jax, sys\n"
        "n = sum(1 for d in jax.local_devices()\n"
        "        if 'tpu' in d.platform.lower()\n"
        "        or 'TPU' in getattr(d, 'device_kind', ''))\n"
        "sys.stdout.write(str(n))\n"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", probe_src], capture_output=True,
            timeout=timeout_s, text=True)
        if out.returncode == 0 and out.stdout.strip().isdigit():
            return int(out.stdout.strip()), pod_type
    except (subprocess.TimeoutExpired, OSError):
        pass
    # Probe failed or timed out: fall back to the environment's claim.
    if pod_type:
        try:
            return int(pod_type.rsplit("-", 1)[1]), pod_type
        except (ValueError, IndexError):
            return 0, pod_type
    return 0, None


def device_kind() -> Optional[str]:
    try:
        import jax

        devices = jax.local_devices()
        return getattr(devices[0], "device_kind", None) if devices else None
    except Exception:
        return None


def peak_flops_per_chip(kind: Optional[str] = None) -> float:
    """Peak bf16 FLOP/s per chip, keyed off the device kind string."""
    kind = (kind or device_kind() or "").lower()
    for gen, flops in sorted(_PEAK_BF16_FLOPS.items(),
                             key=lambda kv: -len(kv[0])):
        if gen in kind:
            return flops
    return _PEAK_BF16_FLOPS["v5e"]


def pod_slice_hosts(pod_type: str) -> int:
    """Number of TPU-VM hosts in a slice, e.g. v5e-16 -> 4 hosts (4 chips/host
    assumed for pod slices; reference derives this from GCE metadata,
    ``tpu.py:198-274``)."""
    chips = int(pod_type.rsplit("-", 1)[1])
    return max(1, chips // _CHIPS_PER_HOST_DEFAULT)


def slice_placement_group(pod_type: str,
                          chips_per_host: int = _CHIPS_PER_HOST_DEFAULT,
                          extra_cpu: float = 1.0):
    """Reserve an entire pod slice as one gang: a STRICT_SPREAD placement
    group with one bundle per TPU-VM host.

    This is the scheduler-native generalization of the reference's
    ``TPU-{pod_type}-head`` resource trick (``tpu.py:362-385``): instead of a
    synthetic head resource plus implicit co-scheduling, every host of the
    slice is explicitly reserved, so trainers can pin one worker per host and
    ``jax.distributed`` forms the mesh across exactly those hosts.
    """
    from ray_tpu.core.placement import placement_group

    n_hosts = pod_slice_hosts(pod_type)
    chips = int(pod_type.rsplit("-", 1)[1])
    per_host_chips = min(chips, chips_per_host)
    bundles: List[Dict[str, float]] = [
        {"TPU": float(per_host_chips), "CPU": extra_cpu}
        for _ in range(n_hosts)
    ]
    return placement_group(bundles, strategy="STRICT_SPREAD")
