"""reactor-safety: no blocking call reachable from a selector callback.

PR 1 existed because a blocking ``send`` on the reactor thread wedged
every connection at once. This checker walks the call graph from the
reactor-thread functions (``rules.REACTOR_ROOT_FUNCS`` plus anything
named ``_on_readable``/``_on_writable``) and flags every blocking
primitive — ``time.sleep``, blocking connect/sendall, unbounded
``acquire``/``wait``/``join``/``result``, subprocess and file I/O —
that the reactor could hit. Calls that cannot be resolved into the
package (dynamic handler dispatch, pool submission) are treated as
opaque: handlers run on the pool, which is exactly the design.
"""

from __future__ import annotations

from typing import Dict, List

from ray_tpu.analysis import rules
from ray_tpu.analysis.callgraph import CallGraph, _short
from ray_tpu.analysis.core import Finding


def _dotted_table() -> Dict[str, str]:
    table = dict(rules.BLOCKING_DOTTED)
    table.update(rules.REACTOR_EXTRA_DOTTED)
    return table


def check(graph: CallGraph, emit_files=None) -> List[Finding]:
    roots = []
    for fqn, info in graph.functions.items():
        tail = info.qualname.rsplit(".", 1)[-1]
        if (any(info.module.endswith(m) and info.qualname == q
                for m, q in rules.REACTOR_ROOT_FUNCS)
                or tail in rules.REACTOR_ROOT_NAME_PATTERNS):
            roots.append(fqn)

    dotted_table = _dotted_table()
    findings: List[Finding] = []
    blocking_map = graph.direct_blocking_map(
        dotted_table, rules.BLOCKING_METHODS_ALWAYS,
        rules.BLOCKING_METHODS_UNBOUNDED)
    # BFS the reactor-reachable set, remembering one path per function.
    paths: Dict[str, List[str]] = {fqn: [_short(fqn)] for fqn in roots}
    queue = list(roots)
    while queue:
        fqn = queue.pop(0)
        info = graph.functions[fqn]
        if emit_files is not None \
                and info.file.relpath not in emit_files:
            # still walk the closure (reachability is whole-program),
            # just skip emission in out-of-slice files
            for callee, _line, _vs in graph.edges().get(fqn, ()):
                if callee not in paths:
                    paths[callee] = paths[fqn] + [_short(callee)]
                    queue.append(callee)
            continue
        for site_line, label in blocking_map.get(fqn, ()):
            via = " -> ".join(paths[fqn])
            findings.append(Finding(
                rule=rules.REACTOR_BLOCKING,
                path=info.file.relpath, line=site_line,
                symbol=info.qualname,
                message=f"blocking call {label} on the reactor thread "
                        f"(reachable via {via})"))
        for callee, _line, _vs in graph.edges().get(fqn, ()):
            if callee not in paths:
                paths[callee] = paths[fqn] + [_short(callee)]
                queue.append(callee)
    return findings
