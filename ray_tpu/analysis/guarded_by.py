"""guarded-by inference: data races on inconsistently-locked fields.

Whole-program, in three steps:

1. **Thread entry points** — ``threading.Thread(target=...)`` /
   ``Timer`` targets, ``executor.submit(fn)`` submissions, the reactor
   callbacks (rules.REACTOR_ROOT_FUNCS), and every RPC handler
   registered in a ``handlers={...}`` map (those run on the server's
   pool — concurrently with THEMSELVES). A synthetic ``caller`` entry
   stands for user threads: every public method and module function.
2. **Thread reachability** — BFS over the resolved call graph from each
   entry; a function is multi-thread-reachable when ≥2 distinct entries
   reach it, or when it is reachable from a self-concurrent entry
   (pool-executed code races against itself).
3. **Guarded-by inference** — per class field (``self._x`` accesses in
   the class's own methods), the lock held at a strict majority of
   eligible access sites (and at ≥ rules.GUARDED_BY_MIN_LOCKED_SITES of
   them) is the field's inferred guard; an exact tie infers nothing.
   Unguarded reads/writes of a guarded field from multi-thread-reachable
   code are flagged.

Noise control, all deliberate: ``__init__``/``__del__``/``__repr__``
sites are construction-time (excluded); ``*_locked``-suffix methods are
called with the lock held by convention (excluded); fields never
written outside excluded methods are effectively immutable (skipped);
lock/condition attributes themselves are skipped.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ray_tpu.analysis import rules
from ray_tpu.analysis.callgraph import CallGraph, FunctionInfo, _short
from ray_tpu.analysis.core import Finding
from ray_tpu.analysis.lock_discipline import (LockId, LockIndex,
                                              lock_index)

_CTOR_TAILS = {d.split(".")[-1] for d in rules.THREAD_CTORS}


@dataclass
class AccessSite:
    fqn: str
    qualname: str
    path: str
    line: int
    is_write: bool
    held: FrozenSet[LockId]
    excluded: bool       # __init__-class method or *_locked convention


def thread_entries(graph: CallGraph
                   ) -> Tuple[Dict[str, Set[str]], Set[str]]:
    """-> (entry key -> root fqns, self-concurrent entry keys)."""
    from ray_tpu.analysis import rpc_contract

    entries: Dict[str, Set[str]] = {}
    self_concurrent: Set[str] = set()

    def add(key: str, fqn: str, concurrent: bool = False) -> None:
        entries.setdefault(key, set()).add(fqn)
        if concurrent:
            self_concurrent.add(key)

    graph.edges()  # ensure the calls_by_tail side index is built
    for fqn, info in graph.functions.items():
        tail = info.qualname.rsplit(".", 1)[-1]
        # reactor callbacks all share THE reactor thread
        if (any(info.module.endswith(m) and info.qualname == q
                for m, q in rules.REACTOR_ROOT_FUNCS)
                or tail in rules.REACTOR_ROOT_NAME_PATTERNS):
            add("reactor", fqn)
    for tail_name in _CTOR_TAILS:
        for node, info in graph.calls_by_tail.get(tail_name, ()):
            rd = graph.resolved_dotted(node, info)
            if rd not in rules.THREAD_CTORS:
                continue
            kw_name, pos_idx = rules.THREAD_CTORS[rd]
            target = None
            for kw in node.keywords:
                if kw.arg == kw_name:
                    target = kw.value
            if target is None and len(node.args) > pos_idx:
                target = node.args[pos_idx]
            tfqn = graph.resolve_callable_expr(target, info) \
                if target is not None else None
            if tfqn is not None and tfqn in graph.functions:
                add(f"thread:{_short(tfqn)}", tfqn)
    for verb in rules.EXECUTOR_SUBMIT_METHODS:
        for node, info in graph.calls_by_tail.get(verb, ()):
            if isinstance(node.func, ast.Attribute) and node.args:
                tfqn = graph.resolve_callable_expr(node.args[0], info)
                if tfqn is not None and tfqn in graph.functions:
                    add(f"pool:{_short(tfqn)}", tfqn, concurrent=True)

    # RPC handlers run on the server's worker pool
    _regs, _inline, handler_fqns = \
        rpc_contract.collect_registrations(graph)
    for name, hfqn in handler_fqns.items():
        add(f"rpc:{name}", hfqn, concurrent=True)

    # synthetic caller entry: public surface invoked from user threads
    for fqn, info in graph.functions.items():
        tail = info.qualname.rsplit(".", 1)[-1]
        if not tail.startswith("_"):
            add("caller", fqn)
    return entries, self_concurrent


def reachability(graph: CallGraph, entries: Dict[str, Set[str]]
                 ) -> Dict[str, Set[str]]:
    """fqn -> set of entry keys whose threads can execute it."""
    edges = graph.edges()
    keys_of: Dict[str, Set[str]] = {}
    for key, roots in entries.items():
        queue = [fqn for fqn in roots]
        seen: Set[str] = set(queue)
        while queue:
            fqn = queue.pop()
            keys_of.setdefault(fqn, set()).add(key)
            for callee, _line, _vs in edges.get(fqn, ()):
                if callee not in seen:
                    seen.add(callee)
                    queue.append(callee)
    return keys_of


def _class_fields(graph: CallGraph, index: LockIndex, module: str,
                  cls: str) -> Dict[str, List[AccessSite]]:
    """field name -> access sites across the class's own methods."""
    ci = graph.classes[(module, cls)]
    lock_attrs = {attr for (m, owner, attr) in index.decls
                  if m == module and owner == cls}
    out: Dict[str, List[AccessSite]] = {}
    for meth_name, fqn in ci.methods.items():
        info = graph.functions.get(fqn)
        if info is None:
            continue
        excluded = meth_name in rules.GUARDED_BY_EXCLUDED_METHODS \
            or meth_name.endswith(rules.LOCKED_BY_CONVENTION_SUFFIX)
        seen = set()
        for site in _method_accesses(index, info, lock_attrs):
            field, line, is_write, held = site
            # one site per (line, kind): `self._q + self._q` is one
            # read site, not two votes in the majority inference
            key = (field, line, is_write, held)
            if key in seen:
                continue
            seen.add(key)
            out.setdefault(field, []).append(AccessSite(
                fqn=fqn, qualname=info.qualname,
                path=info.file.relpath, line=line, is_write=is_write,
                held=held, excluded=excluded))
    return out


def _method_accesses(index: LockIndex, info: FunctionInfo,
                     lock_attrs: Set[str]
                     ) -> List[Tuple[str, int, bool, FrozenSet[LockId]]]:
    """(field, line, is_write, held locks) for every ``self.X`` access,
    tracking the lexical ``with <lock>:`` stack. Nested defs are skipped
    (they execute on their own schedule)."""
    sites: List[Tuple[str, int, bool, FrozenSet[LockId]]] = []

    def record(node: ast.AST, held: Tuple[LockId, ...],
               is_write: bool) -> None:
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self" \
                and node.attr not in lock_attrs:
            sites.append((node.attr, node.lineno, is_write,
                          frozenset(held)))

    def scan_expr(node: ast.AST, held: Tuple[LockId, ...]) -> None:
        for sub in ast.walk(node):
            record(sub, held, isinstance(getattr(sub, "ctx", None),
                                         (ast.Store, ast.Del)))

    def visit(stmts: List[ast.stmt], held: Tuple[LockId, ...]) -> None:
        for node in stmts:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(node, ast.With):
                inner = held
                for item in node.items:
                    lock, _via = index.bind(item.context_expr, info)
                    if lock is not None:
                        inner = inner + (lock,)
                    else:
                        scan_expr(item.context_expr, held)
                    if item.optional_vars is not None:
                        scan_expr(item.optional_vars, held)
                visit(node.body, inner)
                continue
            if isinstance(node, ast.AugAssign):
                # target is both read and written
                record(node.target, held, True)
                scan_expr(node.value, held)
                continue
            # statement-level expressions: walk, excluding nested defs
            for field_name in ("body", "orelse", "finalbody"):
                sub = getattr(node, field_name, None)
                if isinstance(sub, list) and sub \
                        and isinstance(sub[0], ast.stmt):
                    visit(sub, held)
            for h in getattr(node, "handlers", ()):
                visit(h.body, held)
            for sub in ast.iter_child_nodes(node):
                if isinstance(sub, (ast.stmt, ast.FunctionDef,
                                    ast.AsyncFunctionDef, ast.ClassDef,
                                    ast.ExceptHandler)):
                    continue
                scan_expr(sub, held)

    visit(list(info.node.body), ())
    return sites


def _infer_guard(sites: List[AccessSite]
                 ) -> Tuple[Optional[LockId], int, int]:
    """-> (majority lock or None, locked-site count, eligible count)."""
    eligible = [s for s in sites if not s.excluded]
    if not eligible:
        return None, 0, 0
    counts: Dict[LockId, int] = {}
    for s in eligible:
        for lock in s.held:
            counts[lock] = counts.get(lock, 0) + 1
    if not counts:
        return None, 0, len(eligible)
    best = max(counts, key=lambda lk: counts[lk])
    n = counts[best]
    if n < rules.GUARDED_BY_MIN_LOCKED_SITES or n * 2 <= len(eligible):
        return None, n, len(eligible)  # minority or exact tie
    return best, n, len(eligible)


def check(graph: CallGraph, emit_files=None) -> List[Finding]:
    index = lock_index(graph)
    entries, self_concurrent = thread_entries(graph)
    keys_of = reachability(graph, entries)
    findings: List[Finding] = []

    for (module, cls) in sorted(graph.classes):
        if emit_files is not None:
            src = graph.project.by_module.get(module)
            if src is None or src.relpath not in emit_files:
                # a class's fields live in its own file: inference for
                # out-of-slice classes can't produce in-slice findings
                continue
        fields = _class_fields(graph, index, module, cls)
        for field_name, sites in sorted(fields.items()):
            if not any(s.is_write and not s.excluded for s in sites):
                continue  # effectively immutable after construction
            guard, n_locked, n_total = _infer_guard(sites)
            if guard is None:
                continue
            # Contention is a property of the FIELD, not of any single
            # method: a daemon loop mutating it and a public method
            # reading it are two different thread keys even though
            # neither method alone is reachable from two threads.
            field_keys: Set[str] = set()
            concurrent = False
            for s in sites:
                if s.excluded:
                    continue
                ks = keys_of.get(s.fqn, set())
                field_keys |= ks
                concurrent = concurrent or any(
                    k in self_concurrent for k in ks)
            if not (concurrent or len(field_keys) >= 2):
                continue
            for s in sites:
                if s.excluded or guard in s.held:
                    continue
                if not keys_of.get(s.fqn):
                    continue  # unreachable from any entry: dead code
                kind = "written" if s.is_write else "read"
                findings.append(Finding(
                    rule=rules.UNGUARDED_FIELD, path=s.path,
                    line=s.line, symbol=s.qualname,
                    message=f"{cls}.{field_name} is guarded by "
                            f"{guard.label()} at {n_locked}/{n_total} "
                            f"access sites but {kind} without it here; "
                            f"the field is reached from "
                            f"{', '.join(sorted(field_keys)[:4])}"))
    return findings
