"""rpc-contract: string-keyed RPC calls checked against registrations.

The RPC plane is stringly-typed: ``RpcServer(handlers={"name": fn})``
on one side, ``client.call("name", args...)`` on the other, with
nothing but grep keeping them aligned. A renamed handler, a drifted
argument list, or a dead endpoint is invisible until a peer throws at
runtime. Three rules:

* rpc-unknown-method   — a literal ``.call("x")``/``.notify("x")`` whose
                         name is registered by NO server in the package
                         (also: an ``inline_methods`` entry naming no
                         handler).
* rpc-arity-mismatch   — the call's positional/keyword shape cannot be
                         accepted by any registration of that name
                         (client-consumed kwargs like ``timeout`` are
                         excluded; ``*``-splats make a site unchecked).
* rpc-dead-endpoint    — a registered name never called anywhere in the
                         package (attributed to the registration line).
                         Dynamic dispatch (dashboard ``?method=`` proxy)
                         is whitelisted via rules.RPC_DYNAMIC_ENDPOINTS
                         or a pragma on the registration.

Namespace model: the union of all registrations package-wide (the
ISSUE-specified contract). A name registered by several servers is
callable if ANY registration accepts the call's shape.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ray_tpu.analysis import rules
from ray_tpu.analysis.callgraph import CallGraph, FunctionInfo
from ray_tpu.analysis.core import Finding


@dataclass
class Registration:
    name: str                    # RPC method name (the string key)
    path: str                    # file of the registration
    line: int                    # line of the dict key / register call
    symbol: str                  # enclosing function qualname
    # accepted shape, from the handler's signature (None = unresolvable
    # handler: name checking still applies, arity checking is skipped)
    min_pos: Optional[int] = None
    max_pos: Optional[int] = None     # None with has_varargs
    has_varargs: bool = False
    has_kwargs: bool = False
    kw_names: Tuple[str, ...] = ()    # every keyword it can accept
    required_kwonly: Tuple[str, ...] = ()
    handler_fqn: Optional[str] = None  # resolved handler (stub gen)


@dataclass
class CallSite:
    name: str
    path: str
    line: int
    symbol: str
    n_pos: Optional[int]         # None when *args splat present
    kw_names: Tuple[str, ...]
    has_kw_splat: bool
    verb: str                    # call | notify | wrapper name


def _shape_of_arguments(args: ast.arguments, drop_first: bool
                        ) -> Dict[str, object]:
    """Accepted-call shape of a FunctionDef/Lambda ``arguments`` node.
    ``drop_first`` drops the bound ``self``/``cls`` parameter."""
    pos = list(args.posonlyargs) + list(args.args)
    if drop_first and pos:
        pos = pos[1:]
    n_defaults = len(args.defaults)
    min_pos = max(0, len(pos) - n_defaults)
    kwonly = [a.arg for a in args.kwonlyargs]
    required_kwonly = tuple(
        a.arg for a, d in zip(args.kwonlyargs, args.kw_defaults)
        if d is None)
    return {
        "min_pos": min_pos,
        "max_pos": None if args.vararg else len(pos),
        "has_varargs": args.vararg is not None,
        "has_kwargs": args.kwarg is not None,
        # positional params are also addressable by keyword (posonly
        # excluded)
        "kw_names": tuple(a.arg for a in args.args[
            (1 if drop_first and not args.posonlyargs else 0):]
        ) + tuple(kwonly),
        "required_kwonly": required_kwonly,
    }


def _handler_shape(graph: CallGraph, value: ast.AST, ctx: FunctionInfo
                   ) -> Optional[Dict[str, object]]:
    """Shape accepted by a handler-map value expression, or None."""
    if isinstance(value, ast.Lambda):
        return _shape_of_arguments(value.args, drop_first=False)
    fqn = graph.resolve_callable_expr(value, ctx)
    if fqn is None or fqn not in graph.functions:
        return None
    target = graph.functions[fqn]
    is_method = target.cls is not None \
        and "." in target.qualname \
        and not any(_dec_name(d) == "staticmethod"
                    for d in getattr(target.node, "decorator_list", ()))
    return _shape_of_arguments(target.node.args, drop_first=is_method)


def _dec_name(dec: ast.AST) -> Optional[str]:
    if isinstance(dec, ast.Name):
        return dec.id
    if isinstance(dec, ast.Attribute):
        return dec.attr
    return None


def collect_registrations(graph: CallGraph
                          ) -> Tuple[List[Registration],
                                     List[Tuple[str, str, int, str, str]],
                                     Dict[str, str]]:
    """-> (registrations, inline_decls, handler_fqns).

    inline_decls: (name, path, line, symbol, via) for every
    ``inline_methods`` entry. handler_fqns: rpc name -> resolved handler
    fqn where known (guarded-by uses these as pool-thread entry points).
    """
    cached = getattr(graph, "_rpc_registrations", None)
    if cached is not None:
        return cached
    graph.edges()  # ensure the side indexes are built

    regs: List[Registration] = []
    inline: List[Tuple[str, str, int, str, str]] = []
    handler_fqns: Dict[str, str] = {}

    # RpcServer(handlers={...}, inline_methods={...})
    for node, info in graph.calls_by_kwarg.get(
            rules.RPC_HANDLERS_KWARG, ()):
        for kw in node.keywords:
            if kw.arg == rules.RPC_HANDLERS_KWARG \
                    and isinstance(kw.value, ast.Dict):
                for key, value in zip(kw.value.keys, kw.value.values):
                    if not (isinstance(key, ast.Constant)
                            and isinstance(key.value, str)):
                        continue
                    reg = Registration(
                        name=key.value, path=info.file.relpath,
                        line=key.lineno, symbol=info.qualname)
                    shape = _handler_shape(graph, value, info)
                    if shape is not None:
                        for k, v in shape.items():
                            setattr(reg, k, v)
                    hfqn = graph.resolve_callable_expr(value, info)
                    if hfqn is not None and hfqn in graph.functions:
                        handler_fqns.setdefault(key.value, hfqn)
                        reg.handler_fqn = hfqn
                    regs.append(reg)
    for node, info in graph.calls_by_kwarg.get(
            rules.RPC_INLINE_KWARG, ()):
        for kw in node.keywords:
            if kw.arg == rules.RPC_INLINE_KWARG \
                    and isinstance(kw.value, (ast.Set, ast.List,
                                              ast.Tuple)):
                for el in kw.value.elts:
                    if isinstance(el, ast.Constant) \
                            and isinstance(el.value, str):
                        inline.append((el.value, info.file.relpath,
                                       el.lineno, info.qualname,
                                       "inline_methods"))
    # server.register("name", fn) — exactly two positionals with a
    # literal name (gym.register/atexit.register don't match).
    for node, info in graph.calls_by_tail.get(
            rules.RPC_REGISTER_METHOD, ()):
        if isinstance(node.func, ast.Attribute) \
                and not node.keywords and len(node.args) == 2 \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            reg = Registration(
                name=node.args[0].value, path=info.file.relpath,
                line=node.lineno, symbol=info.qualname)
            shape = _handler_shape(graph, node.args[1], info)
            if shape is not None:
                for k, v in shape.items():
                    setattr(reg, k, v)
            hfqn = graph.resolve_callable_expr(node.args[1], info)
            if hfqn is not None and hfqn in graph.functions:
                handler_fqns.setdefault(node.args[0].value, hfqn)
                reg.handler_fqn = hfqn
            regs.append(reg)
    result = (regs, inline, handler_fqns)
    graph._rpc_registrations = result  # memoized: guarded-by reuses it
    return result


def _stub_classes(graph: CallGraph) -> Dict[str, frozenset]:
    """class name -> method names for the generated stub module
    (``<Owner>Stub`` classes in rules.RPC_STUBS_MODULE)."""
    out: Dict[str, frozenset] = {}
    for (mod, cls), ci in graph.classes.items():
        if mod == rules.RPC_STUBS_MODULE and cls.endswith("Stub") \
                and not cls.startswith("_"):
            out[cls] = frozenset(m for m in ci.methods
                                 if not m.startswith("_"))
    return out


def _stub_receiver_class(graph: CallGraph, recv: ast.AST,
                         info: FunctionInfo) -> Optional[str]:
    """The stub class a receiver expression is an instance of, in the
    three migrated spellings: chained ``ControllerStub(c).m(...)``, a
    local alias ``st = ControllerStub(c); st.m(...)``, and a typed
    self-attribute ``self._stub = ControllerStub(c)``."""
    if isinstance(recv, ast.Call):
        hit = graph._class_of_ctor(recv, info)
    elif isinstance(recv, ast.Name):
        alias = info.aliases.get(recv.id)
        if not isinstance(alias, ast.Call):
            return None
        hit = graph._class_of_ctor(alias, info)
    elif isinstance(recv, ast.Attribute) \
            and isinstance(recv.value, ast.Name) \
            and recv.value.id in ("self", "cls") and info.cls is not None:
        hit = graph.self_attr_types.get((info.module, info.cls,
                                         recv.attr))
    else:
        return None
    if hit is not None and hit[0] == rules.RPC_STUBS_MODULE:
        return hit[1]
    return None


def collect_call_sites(graph: CallGraph) -> List[CallSite]:
    graph.edges()  # ensure the side indexes are built
    sites: List[CallSite] = []
    wrappers = rules.RPC_CALL_WRAPPERS
    for verb in tuple(rules.RPC_METHODS) + tuple(wrappers):
        for node, info in graph.calls_by_tail.get(verb, ()):
            if not isinstance(node.func, ast.Attribute):
                continue
            extra = 0
            if verb in wrappers:
                extra, wrapper_module = wrappers[verb]
                if wrapper_module is not None \
                        and info.module != wrapper_module:
                    continue
            if not node.args or not (
                    isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue  # dynamic method name: unchecked
            payload = node.args[1:]
            has_splat = any(isinstance(a, ast.Starred) for a in payload)
            kw_names = tuple(kw.arg for kw in node.keywords
                             if kw.arg is not None
                             and kw.arg not in rules.RPC_CLIENT_KWARGS)
            has_kw_splat = any(kw.arg is None for kw in node.keywords)
            sites.append(CallSite(
                name=node.args[0].value, path=info.file.relpath,
                line=node.lineno, symbol=info.qualname,
                n_pos=None if has_splat else len(payload) + extra,
                kw_names=kw_names, has_kw_splat=has_kw_splat,
                verb=verb))
    # Generated-stub call sites: ``<StubCls>(client).method(...)``-shaped
    # calls are literal uses of the endpoint the method mirrors — they
    # count toward dead-endpoint coverage and get the same shape check
    # (the stub signature mirrors the handler, but a drifted call site
    # should fail HERE, not at the peer). The stub module's own
    # ``self._call(method, ...)`` forwarding is deliberately NOT a use:
    # counting it would mark every endpoint alive.
    stub_cls = _stub_classes(graph)
    if stub_cls:
        for cls, methods in stub_cls.items():
            for meth in methods:
                for node, info in graph.calls_by_tail.get(meth, ()):
                    if not isinstance(node.func, ast.Attribute) \
                            or info.module == rules.RPC_STUBS_MODULE:
                        continue
                    recv_cls = _stub_receiver_class(
                        graph, node.func.value, info)
                    if recv_cls != cls:
                        continue
                    has_splat = any(isinstance(a, ast.Starred)
                                    for a in node.args)
                    kw_names = tuple(
                        kw.arg for kw in node.keywords
                        if kw.arg is not None
                        and kw.arg not in rules.RPC_CLIENT_KWARGS)
                    sites.append(CallSite(
                        name=meth, path=info.file.relpath,
                        line=node.lineno, symbol=info.qualname,
                        n_pos=None if has_splat else len(node.args),
                        kw_names=kw_names,
                        has_kw_splat=any(kw.arg is None
                                         for kw in node.keywords),
                        verb="stub"))
    return sites


def _site_accepted(site: CallSite, reg: Registration) -> Optional[str]:
    """None when the registration accepts the site's shape, else a short
    reason string."""
    if reg.min_pos is None:
        return None  # unresolvable handler: name-only checking
    if site.n_pos is not None:
        if site.n_pos < reg.min_pos:
            # keywords may cover the remaining positional params
            if not site.kw_names and not site.has_kw_splat:
                return (f"{site.n_pos} positional arg(s) for a handler "
                        f"requiring {reg.min_pos}")
        if reg.max_pos is not None and site.n_pos > reg.max_pos:
            return (f"{site.n_pos} positional arg(s) for a handler "
                    f"taking at most {reg.max_pos}")
    if not reg.has_kwargs:
        unknown = [k for k in site.kw_names if k not in reg.kw_names]
        if unknown:
            return f"unknown keyword(s) {', '.join(sorted(unknown))}"
    if reg.required_kwonly and not site.has_kw_splat:
        missing = [k for k in reg.required_kwonly
                   if k not in site.kw_names]
        if missing:
            return (f"missing required keyword-only "
                    f"arg(s) {', '.join(missing)}")
    return None


def check(graph: CallGraph, emit_files=None) -> List[Finding]:
    regs, inline, _handler_fqns = collect_registrations(graph)
    sites = collect_call_sites(graph)
    findings: List[Finding] = []

    by_name: Dict[str, List[Registration]] = {}
    for reg in regs:
        by_name.setdefault(reg.name, []).append(reg)

    # inline_methods entries must name a registered handler
    for name, path, line, symbol, _via in inline:
        if name not in by_name:
            findings.append(Finding(
                rule=rules.RPC_UNKNOWN, path=path, line=line,
                symbol=symbol,
                message=f"inline_methods entry \"{name}\" matches no "
                        f"registered handler"))

    called = set()
    for site in sites:
        called.add(site.name)
        cands = by_name.get(site.name)
        if not cands:
            findings.append(Finding(
                rule=rules.RPC_UNKNOWN, path=site.path, line=site.line,
                symbol=site.symbol,
                message=f".{site.verb}(\"{site.name}\") matches no "
                        f"handler registered anywhere in the package"))
            continue
        reasons = []
        for reg in cands:
            reason = _site_accepted(site, reg)
            if reason is None:
                reasons = []
                break
            reasons.append(reason)
        if reasons:
            findings.append(Finding(
                rule=rules.RPC_ARITY, path=site.path, line=site.line,
                symbol=site.symbol,
                message=f".{site.verb}(\"{site.name}\") rejected by "
                        f"every registration: {reasons[0]} "
                        f"(handler registered at "
                        f"{cands[0].path}:{cands[0].line})"))

    for reg in regs:
        if reg.name in called \
                or reg.name in rules.RPC_DYNAMIC_ENDPOINTS:
            continue
        findings.append(Finding(
            rule=rules.RPC_DEAD, path=reg.path, line=reg.line,
            symbol=reg.symbol,
            message=f"handler \"{reg.name}\" is registered but never "
                    f"called with a literal name anywhere in the "
                    f"package (dynamic-dispatch endpoints: "
                    f"rules.RPC_DYNAMIC_ENDPOINTS or pragma)"))
    if emit_files is not None:
        findings = [f for f in findings if f.path in emit_files]
    return findings
