"""--gen-stubs: typed RPC client stubs generated from the handler index.

The RPC plane is stringly-typed (``client.call("reserve_subslice",
owner, chips, shape)``); rpc_contract polices the strings, but every
call site still re-spells the method name and argument order by hand.
This generator turns rpc_contract's handler index into a checked-in
module (``ray_tpu/core/rpc_stubs.py``) of REAL Python signatures:

    ControllerStub(client).reserve_subslice(owner, chips, shape)
    NodeStub(client).kill_worker(worker_id, force, timeout=5.0)

One ``<Owner>Stub`` class per RpcServer-owning class (Controller, Node,
CoreWorker, ClientServer), one method per registered handler, parameter
names/arity lifted from the handler's signature (``self`` dropped,
defaults preserved as optionality via the ``_UNSET`` sentinel — the
server-side default value stays the single source of truth), plus the
transport's ``timeout`` kwarg on every method. Unresolvable handlers
(lambdas) degrade to ``*args, **kwargs`` passthroughs.

Generation is DETERMINISTIC (classes and methods sorted) so the drift
gate is a straight string compare: the ``rpc-stub-drift`` rule (and
``make lint-stubs-check``) regenerates and fails when a handler
signature changed without rerunning ``--gen-stubs``.

Why generated-and-checked-in instead of built at import time: the stubs
must be greppable, reviewable in diffs when a handler changes, and
importable with zero analysis machinery at runtime.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ray_tpu.analysis import rules
from ray_tpu.analysis.callgraph import CallGraph
from ray_tpu.analysis.core import Finding

_HEADER = '''"""Typed RPC client stubs — GENERATED, do not edit by hand.

Regenerate with ``python -m ray_tpu.analysis --gen-stubs`` whenever a
handler signature changes; ``make lint`` (rpc-stub-drift) and
``make lint-stubs-check`` fail on drift. Each ``<Owner>Stub`` wraps an
RPC client (RpcClient / ReconnectingClient / anything with ``.call``)
and exposes every handler its server registers as a real method —
method names, arities, and the transport ``timeout`` kwarg are checked
by Python itself instead of failing stringly at the peer.

Parameters the handler defaults are declared ``=_UNSET`` and simply
omitted from the wire when not passed, so the SERVER-side default stays
the single source of truth.
"""

from __future__ import annotations

_UNSET = object()


class _StubBase:
    __slots__ = ("_client",)

    def __init__(self, client):
        self._client = client

    def _call(self, method, *args, timeout=_UNSET, **kwargs):
        kwargs = {k: v for k, v in kwargs.items() if v is not _UNSET}
        if timeout is not _UNSET:
            kwargs["timeout"] = timeout
        return self._client.call(method, *args, **kwargs)
'''


def _owner_class(symbol: str, module: str) -> str:
    """Stub-group name for a registration's enclosing symbol:
    ``Controller.__init__`` -> ``Controller``; module-level
    registrations fall back to the module tail, title-cased."""
    head = symbol.split(".")[0]
    if head and head != "<module>" and head[0].isupper():
        return head
    tail = module.rsplit(".", 1)[-1]
    return "".join(p.title() for p in tail.split("_"))


def _fold(prefix: str, parts: List[str], suffix: str) -> str:
    """Greedy line wrap: ``prefix(p1, p2, ...)suffix`` with
    continuations aligned under the open paren, every line <= 78."""
    open_col = len(prefix) + 1
    lines = [prefix + "("]
    for i, part in enumerate(parts):
        tail = part + ("," if i < len(parts) - 1 else suffix)
        if lines[-1].endswith("("):
            cand = lines[-1] + tail
        else:
            cand = lines[-1] + " " + tail
        if len(cand) <= 78:
            lines[-1] = cand
        else:
            lines.append(" " * open_col + tail)
    return "\n".join(lines) + "\n"


def _method_source(graph: CallGraph, name: str,
                   handler_fqn: Optional[str]) -> str:
    """One stub method. Falls back to a passthrough when the handler
    (or an exotic signature) cannot be mirrored faithfully."""
    if not name.isidentifier():
        return ""
    info = graph.functions.get(handler_fqn) if handler_fqn else None
    passthrough = (
        f"    def {name}(self, *args, timeout=_UNSET, **kwargs):\n"
        + _fold("        return self._call",
                [repr(name), "*args", "timeout=timeout", "**kwargs"],
                ")"))
    if info is None:
        return passthrough
    args = info.node.args
    is_method = info.cls is not None and "." in info.qualname and not any(
        isinstance(d, ast.Name) and d.id == "staticmethod"
        for d in getattr(info.node, "decorator_list", ()))
    pos = list(args.posonlyargs) + list(args.args)
    if is_method and pos:
        pos = pos[1:]
    names = [a.arg for a in pos]
    kwonly = [a.arg for a in args.kwonlyargs]
    all_names = names + kwonly
    if args.vararg or args.kwarg or "timeout" in all_names \
            or "self" in all_names or args.posonlyargs \
            or any(not n.isidentifier() for n in all_names):
        return passthrough
    n_req = len(names) - len(args.defaults)
    params, sends = [], [repr(name)]
    for i, n in enumerate(names):
        if i < n_req:
            params.append(n)
            sends.append(n)
        else:
            # defaulted params travel as keywords so an omitted middle
            # arg never shifts later positionals on the wire
            params.append(f"{n}=_UNSET")
            sends.append(f"{n}={n}")
    params.append("*")
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        params.append(a.arg if d is None else f"{a.arg}=_UNSET")
        sends.append(f"{a.arg}={a.arg}")
    params.append("timeout=_UNSET")
    sends.append("timeout=timeout")
    return _fold(f"    def {name}", ["self"] + params, "):") \
        + _fold("        return self._call", sends, ")")


def stub_groups(graph: CallGraph
                ) -> Dict[str, List[Tuple[str, Optional[str]]]]:
    """owner class -> sorted [(rpc name, handler fqn)] from the handler
    index (one entry per name per owner; first registration wins)."""
    from ray_tpu.analysis import rpc_contract

    regs, _inline, _fqns = rpc_contract.collect_registrations(graph)
    groups: Dict[str, Dict[str, Optional[str]]] = {}
    for reg in regs:
        if reg.path == rules.RPC_STUBS_PATH:
            continue  # never self-referential
        owner = _owner_class(reg.symbol, reg.path.replace("/", ".")
                             .removesuffix(".py"))
        groups.setdefault(owner, {}).setdefault(
            reg.name, getattr(reg, "handler_fqn", None))
    return {owner: sorted(methods.items())
            for owner, methods in sorted(groups.items())}


def generate(graph: CallGraph) -> str:
    """The full deterministic source of ray_tpu/core/rpc_stubs.py."""
    out = [_HEADER]
    for owner, methods in stub_groups(graph).items():
        out.append(f"\n\nclass {owner}Stub(_StubBase):\n")
        out.append(f'    """Typed stubs for the {owner} RPC surface '
                   f'(generated)."""\n')
        wrote = False
        for name, fqn in methods:
            src = _method_source(graph, name, fqn)
            if src:
                out.append("\n" + src)
                wrote = True
        if not wrote:
            out.append("\n    pass\n")
    return "".join(out)


def check(graph: CallGraph, emit_files=None) -> List[Finding]:
    """rpc-stub-drift: the checked-in stub module must byte-match what
    the current handler index generates."""
    f = graph.project.by_module.get(rules.RPC_STUBS_MODULE)
    path = rules.RPC_STUBS_PATH
    if f is None:
        finding = Finding(
            rule=rules.RPC_STUB_DRIFT, path=path, line=1,
            symbol="<module>",
            message="generated stub module is missing — run "
                    "`python -m ray_tpu.analysis --gen-stubs`")
    elif f.text != generate(graph):
        finding = Finding(
            rule=rules.RPC_STUB_DRIFT, path=path, line=1,
            symbol="<module>",
            message="stubs are stale vs the current handler index — a "
                    "handler signature changed without regeneration; "
                    "run `python -m ray_tpu.analysis --gen-stubs`")
    else:
        return []
    if emit_files is not None and path not in emit_files:
        return []
    return [finding]
