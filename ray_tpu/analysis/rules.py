"""graftlint rule configuration: the tables the checkers consult.

Everything fitted to THIS codebase's conventions lives here (reactor
roots, blocking-API tables, jit decorator spellings, acquire/release
pairs), so tuning the analyzer never means editing checker logic.
"""

from __future__ import annotations

# --------------------------------------------------------------- rule ids

REACTOR_BLOCKING = "reactor-blocking-call"
TRACE_HOST_SYNC = "trace-host-sync"
TRACE_PY_BRANCH = "trace-python-branch"
TRACE_RETRACE = "trace-retrace-hazard"
LOCK_ORDER_CYCLE = "lock-order-cycle"
LOCK_HELD_BLOCKING = "lock-held-blocking"
SWALLOWED_EXCEPTION = "swallowed-exception"
MISSING_FINALLY = "missing-finally-release"
UNGUARDED_FIELD = "unguarded-field-access"
RESOURCE_LEAK = "resource-leak-path"
RPC_UNKNOWN = "rpc-unknown-method"
RPC_ARITY = "rpc-arity-mismatch"
RPC_DEAD = "rpc-dead-endpoint"
SHARDING_CONTRACTION = "sharding-partitioned-contraction"
SHARDING_ANCHOR = "sharding-missing-anchor"
SHARDING_UNPINNED = "sharding-unpinned-mesh-call"
SHARDING_UNSCOPED = "sharding-unscoped-trace"
RPC_STUB_DRIFT = "rpc-stub-drift"
METRICS_COLLISION = "metrics-name-collision"
METRICS_CARDINALITY = "metrics-label-cardinality"
CHECKPOINT_MISSING = "checkpoint-missing-save"
AUTOPILOT_UNPAIRED = "autopilot-unpaired-action"
FENCE_RESULT_IGNORED = "fence-result-ignored"
FENCE_UNFENCED_MUTATION = "unfenced-mutation-in-fenced-class"
FENCE_COMPARE_DIRECTION = "epoch-compare-direction"
FENCE_EPOCH_NOT_THREADED = "epoch-not-threaded"
DONATION_UNGUARDED = "donation-unguarded-dispatch"
DONATION_ASARRAY_ALIAS = "donation-asarray-alias"
DONATION_READ_AFTER_DONATE = "donation-read-after-donate"
DEADLINE_UNBOUNDED = "unbounded-blocking-call"
DEADLINE_RPC_NO_TIMEOUT = "rpc-call-no-timeout"
DEADLINE_NOT_PROPAGATED = "deadline-not-propagated"
DEADLINE_RETRY_UNBOUNDED = "retry-unbounded"
DEADLINE_KNOB_DEAD = "timeout-knob-dead"
# Not a family rule: emitted centrally by run_analysis on full runs
# (pragma liveness needs EVERY family's raw findings).
STALE_PRAGMA = "stale-pragma"

ALL_RULES = (
    REACTOR_BLOCKING,
    TRACE_HOST_SYNC, TRACE_PY_BRANCH, TRACE_RETRACE,
    LOCK_ORDER_CYCLE, LOCK_HELD_BLOCKING,
    SWALLOWED_EXCEPTION, MISSING_FINALLY,
    UNGUARDED_FIELD,
    RESOURCE_LEAK,
    RPC_UNKNOWN, RPC_ARITY, RPC_DEAD,
    SHARDING_CONTRACTION, SHARDING_ANCHOR,
    SHARDING_UNPINNED, SHARDING_UNSCOPED,
    RPC_STUB_DRIFT,
    METRICS_COLLISION, METRICS_CARDINALITY,
    CHECKPOINT_MISSING,
    AUTOPILOT_UNPAIRED,
    FENCE_RESULT_IGNORED, FENCE_UNFENCED_MUTATION,
    FENCE_COMPARE_DIRECTION, FENCE_EPOCH_NOT_THREADED,
    DONATION_UNGUARDED, DONATION_ASARRAY_ALIAS,
    DONATION_READ_AFTER_DONATE,
    DEADLINE_UNBOUNDED, DEADLINE_RPC_NO_TIMEOUT,
    DEADLINE_NOT_PROPAGATED, DEADLINE_RETRY_UNBOUNDED,
    DEADLINE_KNOB_DEAD,
    STALE_PRAGMA,
)

# The fourteen checker families, for ``--jobs`` scheduling and
# per-family stats: family name -> tuple of rule ids it emits.
# (STALE_PRAGMA is absent by design: pragma liveness is computed in
# run_analysis itself, over every family's pre-suppression findings.)
FAMILIES = {
    "reactor-safety": (REACTOR_BLOCKING,),
    "trace-safety": (TRACE_HOST_SYNC, TRACE_PY_BRANCH, TRACE_RETRACE),
    "lock-discipline": (LOCK_ORDER_CYCLE, LOCK_HELD_BLOCKING),
    "lifecycle-hygiene": (SWALLOWED_EXCEPTION, MISSING_FINALLY),
    "guarded-by": (UNGUARDED_FIELD,),
    "lifetime": (RESOURCE_LEAK, CHECKPOINT_MISSING),
    "rpc-contract": (RPC_UNKNOWN, RPC_ARITY, RPC_DEAD),
    "sharding-safety": (SHARDING_CONTRACTION, SHARDING_ANCHOR,
                        SHARDING_UNPINNED, SHARDING_UNSCOPED),
    "rpc-stubs": (RPC_STUB_DRIFT,),
    "metrics": (METRICS_COLLISION, METRICS_CARDINALITY),
    "autopilot": (AUTOPILOT_UNPAIRED,),
    "fence-safety": (FENCE_RESULT_IGNORED, FENCE_UNFENCED_MUTATION,
                     FENCE_COMPARE_DIRECTION, FENCE_EPOCH_NOT_THREADED),
    "donation-aliasing": (DONATION_UNGUARDED, DONATION_ASARRAY_ALIAS,
                          DONATION_READ_AFTER_DONATE),
    "deadline-safety": (DEADLINE_UNBOUNDED, DEADLINE_RPC_NO_TIMEOUT,
                        DEADLINE_NOT_PROPAGATED,
                        DEADLINE_RETRY_UNBOUNDED, DEADLINE_KNOB_DEAD),
}

# ------------------------------------------------- blocking-API tables

# Dotted call targets that always block the calling thread. Matched
# against the best-effort resolved dotted name at the call site.
BLOCKING_DOTTED = {
    "time.sleep": "time.sleep",
    "socket.create_connection": "blocking connect",
    "subprocess.run": "subprocess",
    "subprocess.call": "subprocess",
    "subprocess.check_call": "subprocess",
    "subprocess.check_output": "subprocess",
    "subprocess.Popen": "subprocess",
    "os.system": "subprocess",
    "os.waitpid": "process wait",
    "shutil.rmtree": "filesystem walk",
}

# Method names that always block regardless of receiver. The reactor's
# own non-blocking socket verbs (recv/send/sendmsg/accept on sockets in
# O_NONBLOCK) are deliberately absent: the analyzer cannot see
# setblocking(False), so it only flags verbs with no non-blocking mode.
BLOCKING_METHODS_ALWAYS = {
    "sendall": "blocking socket send",
    "connect": "blocking connect",
    "recv_into": "blocking socket read",
    "makefile": "socket file I/O",
}

# Method names that block only when called with no bounding argument
# (lock.acquire(), event.wait(), thread.join(), future.result()).
# Any positional or keyword argument is treated as a bound.
BLOCKING_METHODS_UNBOUNDED = {
    "acquire": "unbounded lock acquire",
    "wait": "unbounded wait",
    "join": "unbounded join",
    "result": "unbounded future wait",
}

# Extra table for the LOCK checker only: an RPC issued while holding a
# lock serializes every other path through that lock behind the peer's
# latency. ``.call``/``.notify`` are this runtime's RPC verbs.
RPC_METHODS = {
    "call": "RPC round-trip",
    "notify": "RPC send",
}
RPC_DOTTED = {
    "ray_tpu.get": "blocking object get",
    "ray_tpu.wait": "blocking wait",
    "ray_tpu.kill": "actor-kill RPC",
    "api.get": "blocking object get",
}

# The reactor checker additionally treats file I/O as blocking (a disk
# stall wedges every connection); the lock checker does not (file writes
# under a lock are often the point — e.g. checkpoint serialization).
REACTOR_EXTRA_DOTTED = {
    "open": "file I/O",
}

# ------------------------------------------------------ reactor roots

# (module suffix, function qualname) pairs that run on a reactor /
# selector thread. Name patterns catch conventional callback names in
# future modules without a table edit.
REACTOR_ROOT_FUNCS = {
    ("ray_tpu.core.rpc", "RpcServer._reactor"),
    ("ray_tpu.core.rpc", "RpcServer._accept"),
    ("ray_tpu.core.rpc", "RpcServer._read"),
    ("ray_tpu.core.rpc", "RpcServer._pump"),
    ("ray_tpu.core.rpc", "RpcServer._drop"),
    ("ray_tpu.core.rpc", "RpcServer._drain_ops"),
    ("ray_tpu.core.rpc", "RpcServer._flush"),
    ("ray_tpu.core.rpc", "RpcServer._flush_locked"),
    ("ray_tpu.core.rpc", "RpcServer._set_writing"),
    ("ray_tpu.core.rpc", "RpcServer._send_reply"),
    # _handle runs on the pool for most methods but ON the reactor for
    # inline_methods — it must satisfy reactor discipline.
    ("ray_tpu.core.rpc", "RpcServer._handle"),
}
REACTOR_ROOT_NAME_PATTERNS = ("_on_readable", "_on_writable")

# ---------------------------------------------------- jit decorators

JIT_DOTTED_SUFFIXES = ("jit", "pjit", "shard_map")
# A wrapping call carrying these kwargs is a trace scope regardless of
# what the wrapper is NAMED (aliased imports, partial-built helpers,
# mesh-context jit factories): in/out shardings only mean anything to a
# jit-family compiler, so the wrapped function's body runs under trace
# and every jit hazard (host syncs, tracer branches, retraces) applies.
JIT_SHARDING_KWARGS = frozenset({"in_shardings", "out_shardings"})

# Host-sync method calls that are always wrong under trace.
TRACE_SYNC_METHODS = {
    "item": "host sync (.item())",
    "tolist": "host sync (.tolist())",
    "block_until_ready": "host sync (block_until_ready)",
}
# Dotted host-sync calls (receiver-resolved). ``np``/``numpy`` aliases
# are detected per-module from the import table.
TRACE_SYNC_DOTTED = {
    "jax.device_get": "host transfer (device_get)",
}
NUMPY_SYNC_FUNCS = {"asarray", "array"}

# jnp constructors whose first (or ``shape=``) argument must be static.
SHAPE_POSITION_FUNCS = {"zeros", "ones", "full", "empty", "arange",
                        "broadcast_to"}

# -------------------------------------------- lifecycle acquire/release

# (acquire method name, release method name) — flagged when both appear
# in one function with the release NOT in a ``finally`` block. Lock
# discipline only; resource idioms (sockets, files, selector
# registrations, slots, pins, refcounts) moved to the path-sensitive
# ``resource-leak-path`` rule (lifetime.py).
ACQUIRE_RELEASE_METHODS = (
    ("acquire", "release"),
)
# Dotted acquire constructors -> release method on the result (still
# consulted by the v1 rule for same-function pairing; the v2 lifetime
# rule uses the richer tables below).
ACQUIRE_RELEASE_DOTTED = ()

# ------------------------------------------ v2: guarded-by inference

# Thread-construction call targets -> where the entry callable lives:
# a keyword name, with a positional-index fallback.
THREAD_CTORS = {
    "threading.Thread": ("target", 1),
    "threading.Timer": ("function", 1),
}
# ``X.submit(fn, ...)`` hands fn to a pool thread (and pools run it
# concurrently with itself — self-concurrent, like RPC handlers).
EXECUTOR_SUBMIT_METHODS = ("submit",)

# Methods whose field accesses are construction/teardown-time, excluded
# from guarded-by inference and from flagging.
GUARDED_BY_EXCLUDED_METHODS = ("__init__", "__del__", "__repr__",
                               "__reduce__")
# Methods named ``*_locked`` are called with their lock already held
# (repo convention, e.g. RpcServer._flush_locked): their accesses are
# neither inference evidence nor flaggable.
LOCKED_BY_CONVENTION_SUFFIX = "_locked"

# A field is inferred guarded-by L when L is held at a strict majority
# of its eligible access sites AND at at least this many sites.
GUARDED_BY_MIN_LOCKED_SITES = 2

# ---------------------------------------- v2: resource-lifetime pairing

# Dotted constructors that acquire a releasable resource when assigned
# to a local: ``sock = socket.socket()`` ... ``sock.close()``.
RESOURCE_CTOR_DOTTED = {
    "socket.socket": "close",
    "socket.create_connection": "close",
    "open": "close",
}
# Receiver-keyed acquire/release method pairs: ``sel.register(fd, ...)``
# pairs with ``sel.unregister(fd)`` (possibly in a callee — release-
# through-call is resolved over the call graph), ``cache.pin(h)`` with
# ``cache.unpin(h)``.
RESOURCE_METHOD_PAIRS = {
    "register": "unregister",
    "pin": "unpin",
    # Page-allocator refcount sharing (serve/paging.py): an incref pins
    # a pool page a later decref/free must release.
    "incref": "decref",
    # Pipeline-plane activation-ref ownership (train/pipeline_plane.py
    # RefLedger): ``ledger.borrow_ref(desc)`` registers an in-flight
    # ObjectRef the process keeps alive; ``ledger.drop_ref(desc)`` must
    # run on every exception path (and on stage death, via the
    # _drop_inflight self-callee) — a desc surviving a raise pins its
    # activation tensor cluster-wide, the serve ``_add_replica`` leak
    # shape for ObjectRefs.
    "borrow_ref": "drop_ref",
    # Disaggregated-serving KV-page handoff (serve/handoff.py
    # HandoffLedger): ``self._handoffs.publish_handoff(desc)`` opens a
    # lease over the prefill replica's filled KV pages (pinned in the
    # object store by the descriptor's refs); every escaping exception
    # must discharge it (``discharge_handoff`` — reached via the
    # _drop_handoff self-callee on the adopt-ack/abort/expiry paths) or
    # the pages stay pinned until the TTL sweep. A lease surviving a
    # NORMAL exit is the design: the returned descriptor transfers the
    # discharge obligation to the router splice.
    "publish_handoff": "discharge_handoff",
}
# Slot-pool attributes: ``self._free.pop()`` leases a slot that
# ``self._free.append(slot)`` returns (DecodeEngine slot discipline);
# ``pages = self._pages.alloc(n)`` leases KV pool pages that
# ``self._pages.free(pages)`` returns (the paged-KV allocator — a block
# leak on a cancel/deadline/retire path pins HBM forever, the exact
# failure mode the decode engine's _release_slot centralizes against).
RESOURCE_POOL_ATTRS = {
    "_free": ("pop", "append"),
    "_pages": ("alloc", "free"),
}
# Refcount attributes: ``ent.refcount += 1`` pins, ``-= 1`` unpins
# (prefix-cache row pinning).
RESOURCE_REFCOUNT_ATTRS = ("refcount",)

# --------------------------------------- v3: topology-lease pairing

# RPC-name-keyed lease pairs: ``client.call("reserve_subslice", ...)``
# acquires a topology lease that some ``client.call("release_subslice",
# id)`` (possibly in a self.-callee — the serve controller's
# ``_release_subslice``/``_kill_replica`` chain) must discharge on every
# exception path. Unlike receiver-keyed pairs, leases are GLOBAL (keyed
# by reservation id on the head), so any release call discharges them
# regardless of which client object carries it. A lease surviving a
# normal exit is the design (the replica record owns it); only an
# escaping exception between reserve and release/handoff is a leak —
# a leaked reservation strands its chips until the hosting node dies.
RPC_LEASE_PAIRS = {
    "reserve_subslice": "release_subslice",
    # A host-group registration is a controller-side resource exactly
    # like a sub-slice lease, at GANG granularity: HostGroup._form
    # acquires the group record (and the gang epoch) before spawning
    # members, and a partial-spawn failure must drop it on every
    # exception path alongside the sub-slice release — a leaked record
    # strands the group id and its fencing epoch (the PR 8 _add_replica
    # leak shape, one level up).
    "mh_register_group": "mh_drop_group",
    # A pipeline record (core/pipereg.py) is the same shape at the
    # training plane: PipelinePlane._form_record acquires the record
    # (and its fencing epoch) before pushing stage state, and a partial
    # formation must drop it on every exception path (discharge lives
    # in the _abort_formation self-callee) — a leaked record strands
    # the pipeline id and fences nothing.
    "pipe_register": "pipe_drop",
}
# The RPC verbs lease acquire/release ride on (client.call today;
# notify releases would also discharge).
RPC_LEASE_VERBS = ("call", "notify")

# The CHECKPOINT idiom (the durable-controller twin of the lease
# rule): a control-plane class whose state checkpoints through the
# core KV must reach its save method on EVERY normal exit of its
# state-mutating handlers — a handler that returns without saving
# makes the mutation invisible to the restarted controller (a
# controller death right after it silently reverts the op, orphaning
# replicas / resurrecting deleted apps / losing queued releases).
# class name -> (save method, handlers that must reach it). The save
# may be reached through a self.-callee chain (shutdown -> delete ->
# _save_state counts), resolved over the same summary fixpoint as
# release-through-call. Escaping exceptions are exempt: the handler
# failed, so there may be nothing durable to record.
CHECKPOINT_CLASSES = {
    "ServeController": ("_save_state",
                        ("deploy", "delete", "set_route", "enable_http",
                         "disable_http", "shutdown",
                         "_apply_resize", "_apply_shed")),
}

# ---------------------------------------- autopilot action discipline

# The closed-loop remediator's handler idiom (the RPC_LEASE_PAIRS shape
# applied to control actions): in these modules, every action handler —
# a method whose name carries the action prefix — must PAIR an
# epoch-fence check with a durable audit record. An action that cannot
# show its fence can double-kill a gang the cluster already healed; one
# that cannot show its audit trail is an unaccountable mutation. Both
# calls must appear in the handler body itself (not a transitive
# callee): the pairing is the readable contract.
AUTOPILOT_MODULES = ("ray_tpu/autopilot.py",)
AUTOPILOT_ACTION_PREFIX = "_act_"
AUTOPILOT_FENCE_CALL = "_fence_ok"
AUTOPILOT_AUDIT_CALL = "_audit"

# ------------------------------------------ v3: sharding/mesh safety

# Module holding the logical-axis rule tables, and the names of the
# tables whose contract is BIT-EXACTNESS (no contraction dim ever
# partitions — the GSPMD serving invariant). DEFAULT_RULES (train) is
# also parsed: train tables may shard contraction dims (psum is fine
# for training), but they identify which logical axes CAN shard, which
# is how the row-parallel weights are derived.
SHARDING_RULES_MODULE = "ray_tpu.parallel.sharding"
# ZERO1_STATE_RULES is bit-exact-contracted for a different reason
# than DECODE_RULES: optimizer-state sharding annotations touch only
# elementwise update math, which is safe precisely BECAUSE the table
# never names an axis that sits in contraction position — the moment a
# model axis (embed/heads/mlp/...) is added, the same annotations
# would split reductions of the traced step.
SHARDING_BITEXACT_TABLES = ("DECODE_RULES", "ZERO1_STATE_RULES")
SHARDING_TRAIN_TABLE = "DEFAULT_RULES"
# Module + function names the weight logical-axes tables live in: the
# train table plus the decode overrides (``decode_param_axes`` re-binds
# the row-parallel weights to fully-replicated tuples).
SHARDING_PARAM_AXES_MODULE = "ray_tpu.models.llama"
SHARDING_PARAM_AXES_FUNCS = ("param_axes",)
SHARDING_DECODE_AXES_FUNCS = ("decode_param_axes",)
# Files whose einsum/dot/matmul sites are checked against the tables
# (path prefixes; the sharded model + parallelism code).
SHARDING_SCOPE_PREFIXES = ("ray_tpu/models/", "ray_tpu/parallel/")
# The logical-axis anchor call (``constrain(x, (...axes...))``) —
# matched by trailing name so aliased imports still count.
SHARDING_CONSTRAIN_FUNCS = ("constrain",)
# Mesh-scope spellings: a ``with axis_rules(mesh, rules):`` block, or a
# jit passed through a ``*_mesh_scoped``-style wrapper, marks the
# region where sharded programs are traced.
SHARDING_SCOPE_CTXS = ("axis_rules",)
MESH_SCOPE_WRAPPERS = ("_mesh_scoped",)
# einsum/dot/matmul trailing names checked for contraction hazards.
SHARDING_CONTRACT_FUNCS = ("einsum",)
SHARDING_MATMUL_FUNCS = ("matmul", "dot")

# ------------------------------------------- v3: generated RPC stubs

# The generated typed-stub module (``--gen-stubs``): one ``<Cls>Stub``
# class per RpcServer owner, methods mirroring handler signatures.
# Stub-method call sites count as literal RPC uses (dead-endpoint +
# arity checking); the module itself is gated against drift by the
# ``rpc-stub-drift`` rule and ``make lint-stubs-check``.
RPC_STUBS_MODULE = "ray_tpu.core.rpc_stubs"
RPC_STUBS_PATH = "ray_tpu/core/rpc_stubs.py"

# ------------------------------------------- v2: RPC contract checking

# Handler maps are declared as RpcServer(handlers={...}) dict literals
# (this keyword) or via server.register("name", fn).
RPC_HANDLERS_KWARG = "handlers"
RPC_INLINE_KWARG = "inline_methods"
RPC_REGISTER_METHOD = "register"
# Client-side kwargs consumed by the transport, never forwarded to the
# handler.
RPC_CLIENT_KWARGS = ("timeout",)
# Wrapper methods that prepend implicit positional args before
# forwarding to ``.call`` (ClientCore._call prepends the session id).
# Scoped to the module defining the wrapper: an unrelated ``_call``
# (tpu_vm_api's HTTP helper) must not be read as an RPC site.
RPC_CALL_WRAPPERS = {
    "_call": (1, "ray_tpu.client"),
}
# Endpoints reached only through dynamic dispatch the AST cannot see
# (dashboard proxy forwards ?method=... query strings; CLI tools) or
# from outside the package (tests, external health probes).
# Registered-but-never-literally-called names listed here are not dead.
RPC_DYNAMIC_ENDPOINTS: frozenset = frozenset({
    # liveness probe on every server: exercised by tests, health
    # monitors, and the dashboard's generic proxy
    "ping",
})

# ------------------------------------- metrics label cardinality (#10)

# Metric-record method names whose tags dict is inspected for unbounded
# label values (tags= kwarg, the post-value positional, or the sole
# argument of set_default_tags).
METRICS_RECORD_METHODS = frozenset({"inc", "set", "observe",
                                    "observe_many", "set_default_tags"})
# Terminal identifier names that denote a per-request/object/task id —
# unbounded label cardinality (one registry series per request never
# merges and eventually evicts bounded series from the snapshot cap).
# Matched against the LAST attribute/name segment of any sub-expression
# of a label value; names merely ENDING in "_id" also match.
METRICS_ID_NAMES = frozenset({"oid", "uuid", "request", "req_id"})
METRICS_ID_SUFFIX = "_id"
# Calls whose result is id-shaped regardless of receiver (oid.hex(),
# uuid.uuid4()): flagged as label values.
METRICS_ID_CALLS = frozenset({"hex", "uuid4", "uuid1"})

# ------------------------------- flight-recorder event names (#10)

# Flight-recorder record() sites (import-resolved to this module) go
# through the same literal-name discipline as metric constructors: one
# event name, one attr-key schema (the post-mortem merges events by
# name — a site recording the same name with different keys silently
# breaks every downstream grouping), and id-shaped attr VALUES flagged
# exactly like metric label values (the ring is bounded, but an event
# whose attrs are per-request ids is a metric trying to be born).
FLIGHTREC_MODULE = "ray_tpu.util.flightrec"
FLIGHTREC_RECORD_FUNC = "record"
# audit() is record()+flush_now() (durable variant, PR 18): an audit
# site defines an event schema exactly like a record site does.
FLIGHTREC_RECORD_FUNCS = (FLIGHTREC_RECORD_FUNC, "audit")
# Attr keys whose values are bounded schedule/geometry integers by
# construction ({step, mb, stage} and friends): exempt from the
# id-shaped check — `step=self._step` is a clock, not a cardinality
# hazard.
FLIGHTREC_BOUNDED_ATTRS = frozenset({
    "step", "mb", "stage", "epoch", "asked", "mbs", "attempt", "hosts",
    "stages", "chips", "current", "n"})

# ------------------------------------ v4: epoch-fence protocol (#12)

# Fenced write APIs whose RESULT is the stale-epoch verdict: a caller
# that discards it keeps acting as the owner after being deposed (the
# split-brain the fencing exists to prevent). Matched by call tail
# (stub methods and direct handler calls) and by the string form
# ``client.call("<name>", ...)``. The autopilot's fenced actions ride
# mh_group_put, so its handlers are covered by this same table (the
# _fence_ok/_audit PAIRING is family #11's job).
FENCED_WRITE_APIS = {
    "kv_put_fenced": "False == stale epoch: the writer was deposed",
    "mh_group_put": '{"ok": False, "reason": "stale_epoch"} == deposed',
    "pipe_step_complete": '{"ok": False} == stale incarnation',
}
# Publish-shaped APIs are fenced ONLY when an epoch rides the call
# (the hub treats epoch=None as an unfenced write — there is no stale
# verdict to consume): name -> (epoch kwarg, its positional index).
FENCED_WRITE_EPOCH_ARG = {
    "publish": ("epoch", 4),
    "psub_publish": ("epoch", 4),
}
# RPC verbs carrying the string form of a fenced write; ``notify`` is
# fire-and-forget by design, so only result-returning verbs count.
FENCED_RPC_VERBS = ("call",)

# Classes whose controller-KV / pubsub state is epoch-fenced: every
# mutating write from these classes must go through the fenced API
# (kv_put_fenced / an epoch-carrying publish) — the raw spellings
# listed here bypass the fence and re-open the PR 12 split-brain.
# The core Controller itself (the KV owner) is deliberately absent:
# it IS the fence.
FENCED_STATE_CLASSES = {
    "ServeController": ("kv_put", "kv_del"),
    "Autopilot": ("kv_put", "kv_del"),
}

# Epoch/version comparison sites: (path, dotted suffix of the STORED
# clock, mode). mode "equal-ok" = stale iff STRICTLY older (the
# serve-snapshot rule: a same-epoch republish must be accepted — a
# normalized ``incoming <= stored`` / ``incoming > stored`` guard
# drops legitimate same-epoch writes); mode "strict" = strictly-newer
# -wins (the WeightFanout/receiver rule: an equal version is a replay
# — a normalized ``incoming < stored`` / ``incoming >= stored`` guard
# re-applies it). Comparisons against literal constants are not
# protocol checks and are ignored.
EPOCH_COMPARE_TABLE = (
    ("ray_tpu/core/controller.py", "current", "equal-ok"),
    ("ray_tpu/core/multihost.py", "rec.epoch", "equal-ok"),
    ("ray_tpu/core/pipereg.py", "rec.epoch", "equal-ok"),
    ("ray_tpu/core/pubsub.py", "cur_epoch", "equal-ok"),
    ("ray_tpu/serve/deployment.py", "self._ctrl_epoch", "equal-ok"),
    ("ray_tpu/serve/controller.py", "self._epoch", "equal-ok"),
    ("ray_tpu/rl/distributed/fanout.py", "self._version", "strict"),
    ("ray_tpu/rl/distributed/fanout.py", "self._weights_version",
     "strict"),
    ("ray_tpu/rl/distributed/learner.py", "self._last_version",
     "equal-ok"),
)

# Fenced publishes whose PAYLOAD must carry the clock: (class, call
# tail) -> (payload positional index, required literal key). A
# subscriber that cannot read the epoch/version out of the payload
# cannot run its own staleness check (the router-snapshot idiom).
# Only dict-literal payloads (direct or via a same-function local)
# are checked — an opaque payload expression is not evidence.
FENCED_PAYLOAD_RULES = {
    ("ServeController", "psub_publish"): (2, "epoch"),
    ("HostGroup", "mh_group_put"): (2, "epoch"),
    ("WeightFanout", "psub_publish"): (2, "version"),
}

# --------------------------------- v4: donated-buffer aliasing (#13)

# Guard wrappers a donated program's dispatch must flow through:
# ``self._dispatch_fresh(key, lambda: self._prog(...))`` detaches the
# persistent XLA cache on the FIRST dispatch (jaxlib 0.4.37, PR 14: a
# donated executable reloaded from the disk cache segfaults or
# returns wrong numbers). Dispatch inside the guard's own body is the
# guard working, not a violation.
DONATED_DISPATCH_GUARDS = ("_dispatch_fresh",)
# Keyword spellings that mark a jit construction as donating.
DONATION_JIT_KWARGS = ("donate_argnums", "donate")

# -------------------------------------- v5: deadline safety (#20)

# Wait verbs the unbounded-blocking-call rule polices, with where their
# finite bound lives: verb -> (timeout kwarg name, its positional
# index, label). Bounded = that argument is present and is not the
# literal ``None`` (a Name/attribute/call expression counts as a bound
# — config knobs are floats and ``Deadline.remaining()`` never returns
# a forever value for a bounded deadline). ``get`` is checked only on
# stdlib-queue-typed receivers (DEADLINE_QUEUE_CTORS): bare ``.get``
# is dict/contextvar territory.
DEADLINE_WAIT_VERBS = {
    "wait": ("timeout", 0, "unbounded wait"),
    "join": ("timeout", 0, "unbounded join"),
    "result": ("timeout", 0, "unbounded future wait"),
    "get": ("timeout", 1, "unbounded queue get"),
}
# For ``get`` only: a literal-False first positional / ``block=False``
# makes the call non-blocking, which is as bounded as it gets.
DEADLINE_NONBLOCK_KWARG = "block"
# Queue constructors that type a local / self-attribute as a blocking
# queue for the ``get`` verb (dotted, import-resolved). The in-repo
# util.queue twins keep the stdlib signature, so the same timeout
# position applies.
DEADLINE_QUEUE_CTORS = {
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue", "multiprocessing.Queue",
    "ray_tpu.util.queue.Queue", "ray_tpu.util.queue.ShardedQueue",
}
# Socket read verbs: no timeout argument exists — the bound is
# ``settimeout``/``setblocking`` on the socket. A module that calls
# either anywhere manages its own socket modes (the reactor's
# nonblocking fds, _connect's bounded dial); flagged only when the
# enclosing MODULE shows neither.
DEADLINE_SOCKET_VERBS = ("recv", "recv_into")
DEADLINE_SOCKET_MODE_CALLS = ("settimeout", "setblocking")

# rpc-call-no-timeout scope: control-plane modules where every literal
# ``.call("name", ...)`` / typed-stub call must carry ``timeout=`` (or
# the documented config default below). Data-plane and long-poll
# surfaces (pubsub subscribe parks, object-plane streams) are
# deliberately out of scope: their unbounded waits are the design, and
# rule 1 still covers their thread entries.
DEADLINE_RPC_SCOPE_PREFIXES = (
    "ray_tpu/core/multihost.py",
    "ray_tpu/core/pipereg.py",
    "ray_tpu/serve/controller.py",
    "ray_tpu/serve/proxy.py",
    "ray_tpu/serve/deployment.py",
    "ray_tpu/serve/handoff.py",
    "ray_tpu/train/pipeline_plane.py",
    "ray_tpu/autopilot.py",
)
# Parameters NAMED as stubs are stub-typed receivers too: helpers that
# take the constructed stub (``def _abort_formation(self, stub, ...)``)
# make the same control-plane calls as their caller.
DEADLINE_STUB_PARAM_NAMES = ("stub",)
DEADLINE_STUB_PARAM_SUFFIX = "_stub"
# Timeout-default documentation: config knob -> the wait sites it is
# expected to bound (module path prefix, call tail). Doubles as the
# dead-knob cross-check's allowlist of intent — a ``*_timeout_s`` knob
# in core/config.py that no package code ever READS (no
# ``config.<knob>`` attribute access) is flagged timeout-knob-dead,
# mirroring rpc-dead-endpoint.
DEADLINE_KNOB_SUFFIX = "_timeout_s"
DEADLINE_CONFIG_MODULE_PATH = "ray_tpu/core/config.py"
DEADLINE_CONFIG_FLAGS_NAME = "_FLAG_DEFS"

# deadline-not-propagated: parameter names that carry a caller's time
# budget. A function taking one and making 2+ deadline-relevant calls
# (wait verbs / scoped RPC) must show a remaining-time idiom —
# ``Deadline`` usage (DEADLINE_IDIOM_ATTRS / the helper module) or raw
# ``time.monotonic()`` arithmetic. Exactly ONE downstream site
# consuming the budget is a pass-through, not a violation
# (RpcClient.call -> pending.wait(timeout) is the exemplar).
DEADLINE_PARAM_NAMES = ("timeout_s", "timeout", "deadline",
                        "deadline_s", "timeout_seconds")
DEADLINE_IDIOM_ATTRS = ("remaining", "expired", "sub")
DEADLINE_IDIOM_DOTTED = ("time.monotonic",)
DEADLINE_HELPER_MODULE = "ray_tpu.util.deadline"

# retry-unbounded: an unconditionally-true loop (``while True`` /
# ``itertools.count``) re-issuing dial/RPC verbs with no bounding
# signal in the body. Bounding signals (any one suffices): a backoff
# sleep, an attempt counter compared in body or loop test, a deadline
# check (DEADLINE_IDIOM_ATTRS / time.monotonic), or a non-constant
# loop test. The PR 12 reconnect storm, caught statically.
DEADLINE_RETRY_VERBS = ("call", "notify", "create_connection",
                        "connect", "dial")
DEADLINE_BACKOFF_CALLS = ("sleep", "backoff", "uniform")
