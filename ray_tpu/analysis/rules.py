"""graftlint rule configuration: the tables the checkers consult.

Everything fitted to THIS codebase's conventions lives here (reactor
roots, blocking-API tables, jit decorator spellings, acquire/release
pairs), so tuning the analyzer never means editing checker logic.
"""

from __future__ import annotations

# --------------------------------------------------------------- rule ids

REACTOR_BLOCKING = "reactor-blocking-call"
TRACE_HOST_SYNC = "trace-host-sync"
TRACE_PY_BRANCH = "trace-python-branch"
TRACE_RETRACE = "trace-retrace-hazard"
LOCK_ORDER_CYCLE = "lock-order-cycle"
LOCK_HELD_BLOCKING = "lock-held-blocking"
SWALLOWED_EXCEPTION = "swallowed-exception"
MISSING_FINALLY = "missing-finally-release"

ALL_RULES = (
    REACTOR_BLOCKING,
    TRACE_HOST_SYNC, TRACE_PY_BRANCH, TRACE_RETRACE,
    LOCK_ORDER_CYCLE, LOCK_HELD_BLOCKING,
    SWALLOWED_EXCEPTION, MISSING_FINALLY,
)

# ------------------------------------------------- blocking-API tables

# Dotted call targets that always block the calling thread. Matched
# against the best-effort resolved dotted name at the call site.
BLOCKING_DOTTED = {
    "time.sleep": "time.sleep",
    "socket.create_connection": "blocking connect",
    "subprocess.run": "subprocess",
    "subprocess.call": "subprocess",
    "subprocess.check_call": "subprocess",
    "subprocess.check_output": "subprocess",
    "subprocess.Popen": "subprocess",
    "os.system": "subprocess",
    "os.waitpid": "process wait",
    "shutil.rmtree": "filesystem walk",
}

# Method names that always block regardless of receiver. The reactor's
# own non-blocking socket verbs (recv/send/sendmsg/accept on sockets in
# O_NONBLOCK) are deliberately absent: the analyzer cannot see
# setblocking(False), so it only flags verbs with no non-blocking mode.
BLOCKING_METHODS_ALWAYS = {
    "sendall": "blocking socket send",
    "connect": "blocking connect",
    "recv_into": "blocking socket read",
    "makefile": "socket file I/O",
}

# Method names that block only when called with no bounding argument
# (lock.acquire(), event.wait(), thread.join(), future.result()).
# Any positional or keyword argument is treated as a bound.
BLOCKING_METHODS_UNBOUNDED = {
    "acquire": "unbounded lock acquire",
    "wait": "unbounded wait",
    "join": "unbounded join",
    "result": "unbounded future wait",
}

# Extra table for the LOCK checker only: an RPC issued while holding a
# lock serializes every other path through that lock behind the peer's
# latency. ``.call``/``.notify`` are this runtime's RPC verbs.
RPC_METHODS = {
    "call": "RPC round-trip",
    "notify": "RPC send",
}
RPC_DOTTED = {
    "ray_tpu.get": "blocking object get",
    "ray_tpu.wait": "blocking wait",
    "ray_tpu.kill": "actor-kill RPC",
    "api.get": "blocking object get",
}

# The reactor checker additionally treats file I/O as blocking (a disk
# stall wedges every connection); the lock checker does not (file writes
# under a lock are often the point — e.g. checkpoint serialization).
REACTOR_EXTRA_DOTTED = {
    "open": "file I/O",
}

# ------------------------------------------------------ reactor roots

# (module suffix, function qualname) pairs that run on a reactor /
# selector thread. Name patterns catch conventional callback names in
# future modules without a table edit.
REACTOR_ROOT_FUNCS = {
    ("ray_tpu.core.rpc", "RpcServer._reactor"),
    ("ray_tpu.core.rpc", "RpcServer._accept"),
    ("ray_tpu.core.rpc", "RpcServer._read"),
    ("ray_tpu.core.rpc", "RpcServer._pump"),
    ("ray_tpu.core.rpc", "RpcServer._drop"),
    ("ray_tpu.core.rpc", "RpcServer._drain_ops"),
    ("ray_tpu.core.rpc", "RpcServer._flush"),
    ("ray_tpu.core.rpc", "RpcServer._flush_locked"),
    ("ray_tpu.core.rpc", "RpcServer._set_writing"),
    ("ray_tpu.core.rpc", "RpcServer._send_reply"),
    # _handle runs on the pool for most methods but ON the reactor for
    # inline_methods — it must satisfy reactor discipline.
    ("ray_tpu.core.rpc", "RpcServer._handle"),
}
REACTOR_ROOT_NAME_PATTERNS = ("_on_readable", "_on_writable")

# ---------------------------------------------------- jit decorators

JIT_DOTTED_SUFFIXES = ("jit", "pjit", "shard_map")

# Host-sync method calls that are always wrong under trace.
TRACE_SYNC_METHODS = {
    "item": "host sync (.item())",
    "tolist": "host sync (.tolist())",
    "block_until_ready": "host sync (block_until_ready)",
}
# Dotted host-sync calls (receiver-resolved). ``np``/``numpy`` aliases
# are detected per-module from the import table.
TRACE_SYNC_DOTTED = {
    "jax.device_get": "host transfer (device_get)",
}
NUMPY_SYNC_FUNCS = {"asarray", "array"}

# jnp constructors whose first (or ``shape=``) argument must be static.
SHAPE_POSITION_FUNCS = {"zeros", "ones", "full", "empty", "arange",
                        "broadcast_to"}

# -------------------------------------------- lifecycle acquire/release

# (acquire method name, release method name) — flagged when both appear
# in one function with the release NOT in a ``finally`` block.
ACQUIRE_RELEASE_METHODS = (
    ("acquire", "release"),
    ("register", "unregister"),
)
# Dotted acquire constructors -> release method on the result.
ACQUIRE_RELEASE_DOTTED = (
    ("socket.socket", "close"),
    ("open", "close"),
)
