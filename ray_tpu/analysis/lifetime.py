"""resource-leak-path: path-sensitive acquire/release pairing.

Walks each function as a structured control-flow interpreter (if/else
splits, loop bodies, try/except/finally with exception edges) carrying
the set of live resources, and flags any path that exits the function —
by return, fall-through, or an escaping exception — while a resource is
still live and unowned. Resource idioms are fitted to this codebase
(rules.py tables):

* local constructors — ``sock = socket.socket()`` / ``open()`` paired
  with ``.close()`` (a ``with`` form is never tracked: the context
  manager releases);
* receiver-keyed pairs — ``sel.register(...)``/``sel.unregister(...)``,
  ``cache.pin(...)``/``unpin``; release may happen in a self.-callee
  (``_drop``-style teardown helpers), resolved over the call graph.
  Tracked only in functions that DO release the pair somewhere —
  a function that never releases owns the registration by design
  (``__init__`` registering the listening socket);
* slot pools — ``self._free.pop()`` leases, ``self._free.append(s)``
  returns (DecodeEngine slots); ``pages = self._pages.alloc(n)``
  leases KV pool pages, ``self._pages.free(...)`` returns them — the
  leased local matches anywhere inside a release argument expression
  (``free(shared + fresh)``), since the paged-KV allocator frees
  collections;
* refcounts — ``ent.refcount += 1`` pins, ``-= 1`` unpins (prefix-cache
  rows); ``alloc.incref(p)``/``decref(p)`` pin/unpin pool pages
  (method-pair form);
* topology leases — ``sub = client.call("reserve_subslice", ...)``
  acquires a lease that ``client.call("release_subslice", id)`` (on ANY
  client object — leases are keyed by reservation id on the head, not
  by the receiver; release may live in a self.-callee like the serve
  controller's ``_release_subslice``/``_kill_replica`` chain) must
  discharge on every exception path. Like receiver-keyed pairs, a lease
  surviving a *normal* exit is the design (the replica record owns it);
  only an escaping exception between reserve and release/handoff leaks
  — the stranded reservation pins its chips until the hosting node
  dies. Handoff is recognized when the lease local is passed as a BARE
  argument to any call (``ReplicaRecord(handle, rid, sub)``) — nested
  reads (``chip_resources(sub["chips"], ...)``) stay borrows.

Ownership transfer kills liveness: storing the resource (assignment
value — including wrapping constructors like ``_Conn(sock)``),
returning or yielding it, or raising with it. Passing it as a bare
call argument is a *borrow* (the callee is not assumed to close it).
Generator functions are skipped (suspension makes path-exit analysis
meaningless).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ray_tpu.analysis import rules
from ray_tpu.analysis.callgraph import (CallGraph, FunctionInfo, dotted,
                                        _walk_no_nested)
from ray_tpu.analysis.core import Finding

State = FrozenSet[int]
Outcomes = Dict[str, Set[Tuple[State, int]]]
_EXITS = ("fall", "return", "raise", "break", "continue")


@dataclass
class Resource:
    rid: int
    kind: str            # ctor | pair | pool | ref | lease | ckpt
    name: Optional[str]  # local var holding it (ctor/pool/lease)
    recv_key: Optional[str]   # receiver dotted key (pair/ref/pool),
    #                           "rpc:<release name>" (lease), or
    #                           "ckpt:<save method>" (ckpt)
    release_verb: str
    label: str
    line: int
    node_id: int         # id() of the acquire AST node


_LEASE_NAMES = frozenset(rules.RPC_LEASE_PAIRS) \
    | frozenset(rules.RPC_LEASE_PAIRS.values())
# Save-method names of the checkpoint idiom (checkpoint-missing-save):
# a state-mutating handler "acquires" dirty state at entry and must
# discharge it by reaching the class's save method on every normal exit.
_CKPT_SAVES = frozenset(save for save, _methods
                        in rules.CHECKPOINT_CLASSES.values())


def _ckpt_entry(info: FunctionInfo):
    """(save_method, label) when ``info`` is a handler the checkpoint
    table obliges to save, else None."""
    if info.cls is None:
        return None
    entry = rules.CHECKPOINT_CLASSES.get(info.cls)
    if entry is None:
        return None
    save, methods = entry
    name = getattr(info.node, "name", "")
    if name in methods:
        return save, f"state mutation in {info.cls}.{name}"
    return None


def _lease_rpc_name(node: ast.AST) -> Optional[str]:
    """The RPC name of a lease-pair site, in either spelling: the raw
    ``.call("reserve_subslice", ...)`` verb form, or the generated-stub
    method form (``stub.reserve_subslice(...)`` — the method name IS
    the endpoint name, core/rpc_stubs.py)."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)):
        return None
    if node.func.attr in rules.RPC_LEASE_VERBS and node.args \
            and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        name = node.args[0].value
        return name if name in _LEASE_NAMES else None
    if node.func.attr in _LEASE_NAMES:
        return node.func.attr
    return None


def _release_summaries(graph: CallGraph) -> Dict[str, Set[Tuple[str, str]]]:
    """fqn -> {(recv dotted, verb)} released directly or via self-call
    chains (one fixpoint): lets ``self._drop(st)`` count as the
    ``self._selector.unregister`` it performs."""
    verbs = set(rules.RESOURCE_METHOD_PAIRS.values())
    graph.edges()  # ensure the side indexes are built
    direct: Dict[str, Set[Tuple[str, str]]] = {
        fqn: set() for fqn in graph.functions}
    for verb in verbs:
        for node, info in graph.calls_by_tail.get(verb, ()):
            if isinstance(node.func, ast.Attribute):
                d = dotted(node.func.value)
                if d is not None:
                    direct[info.fqn].add((d, verb))
    for node, info in graph.attr_augassigns:
        if node.target.attr in rules.RESOURCE_REFCOUNT_ATTRS \
                and isinstance(node.op, ast.Sub):
            d = dotted(node.target.value)
            if d is not None:
                direct[info.fqn].add((d, "refdec"))
    lease_releases = set(rules.RPC_LEASE_PAIRS.values())
    for tail in tuple(rules.RPC_LEASE_VERBS) + tuple(lease_releases):
        for node, info in graph.calls_by_tail.get(tail, ()):
            name = _lease_rpc_name(node)
            if name in lease_releases:
                direct[info.fqn].add((f"rpc:{name}", name))
    # checkpoint saves: ``self._save_state()`` on a method's NORMAL
    # path counts, and propagates through self-call chains below
    # (delete -> _save_state discharges a caller's obligation). Saves
    # lexically inside an ``except`` handler are excluded from the
    # summary: a callee that only checkpoints on its failure path
    # (_release_reservation queueing a failed release) does not
    # discharge its caller — summaries are path-insensitive, so
    # without this exclusion every handler that can reach
    # _kill_replica would count as checkpointed.
    for save in _CKPT_SAVES:
        except_ids: Dict[str, Set[int]] = {}
        for node, info in graph.calls_by_tail.get(save, ()):
            if not (isinstance(node.func, ast.Attribute)
                    and dotted(node.func.value) == "self"):
                continue
            ids = except_ids.get(info.fqn)
            if ids is None:
                ids = set()
                for n in _walk_no_nested(info.node):
                    if isinstance(n, ast.Try):
                        for handler in n.handlers:
                            ids.update(id(sub) for sub
                                       in ast.walk(handler))
                except_ids[info.fqn] = ids
            if id(node) in ids:
                continue
            direct[info.fqn].add((f"ckpt:{save}", save))

    closure = {fqn: set(rel) for fqn, rel in direct.items()}
    changed = True
    iters = 0
    while changed and iters < 10:
        changed = False
        iters += 1
        for fqn, rows in graph.edges().items():
            cur = closure.get(fqn)
            if cur is None:
                continue
            before = len(cur)
            for callee, _line, via_self in rows:
                if via_self and callee in closure:
                    # only self.-keyed releases survive the hop (the
                    # callee's ``self`` is the caller's ``self``); lease
                    # releases are global (reservation-id keyed on the
                    # head) and checkpoint saves are self-keyed by
                    # construction, so they survive too
                    cur.update(k for k in closure[callee]
                               if k[0].startswith(("self.", "rpc:",
                                                   "ckpt:")))
            if len(cur) != before:
                changed = True
    return closure


def _collect_resources(graph: CallGraph, info: FunctionInfo,
                       summaries: Dict[str, Set[Tuple[str, str]]]
                       ) -> List[Resource]:
    """Acquire sites in this function, per the rules tables."""
    out: List[Resource] = []
    with_ctx_ids: Set[int] = set()
    for node in _walk_no_nested(info.node):
        if isinstance(node, ast.With):
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    with_ctx_ids.add(id(sub))

    # does this function release (recv_key, verb) anywhere, directly or
    # through a self-call? precondition for pair tracking.
    def releases_somewhere(recv_key: str, verb: str) -> bool:
        for node in _walk_no_nested(info.node):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                if node.func.attr == verb \
                        and dotted(node.func.value) == recv_key:
                    return True
                callee, _vs = graph.resolve_call_cached(node, info)
                if callee is not None and recv_key.startswith("self.") \
                        and (recv_key, verb) in summaries.get(callee,
                                                              ()):
                    return True
            elif verb == "refdec" and isinstance(node, ast.AugAssign) \
                    and isinstance(node.target, ast.Attribute) \
                    and node.target.attr in rules.RESOURCE_REFCOUNT_ATTRS \
                    and isinstance(node.op, ast.Sub) \
                    and dotted(node.target.value) == recv_key:
                return True
        return False

    rid = 0
    # checkpoint obligation: "acquired" at function ENTRY (the handler
    # is about to mutate durable state), discharged only by reaching
    # the save method (directly or via a self-callee). Seeded into the
    # interpreter's initial state by _FnAnalysis.run.
    ckpt = _ckpt_entry(info)
    if ckpt is not None:
        save, label = ckpt
        out.append(Resource(rid, "ckpt", None, f"ckpt:{save}", save,
                            label, info.node.lineno, id(info.node)))
        rid += 1
    for node in _walk_no_nested(info.node):
        # ctor acquires: x = socket.socket(...) / open(...)
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and id(node.value) not in with_ctx_ids:
            rd = graph.resolved_dotted(node.value, info)
            if rd in rules.RESOURCE_CTOR_DOTTED:
                out.append(Resource(
                    rid, "ctor", node.targets[0].id, None,
                    rules.RESOURCE_CTOR_DOTTED[rd], rd, node.lineno,
                    id(node)))
                rid += 1
                continue
            # topology lease: sub = client.call("reserve_subslice", ...)
            rpc_name = _lease_rpc_name(node.value)
            if rpc_name in rules.RPC_LEASE_PAIRS:
                release = rules.RPC_LEASE_PAIRS[rpc_name]
                out.append(Resource(
                    rid, "lease", node.targets[0].id, f"rpc:{release}",
                    release, f'call("{rpc_name}") lease', node.lineno,
                    id(node)))
                rid += 1
                continue
            # pool lease: slot = self._free.pop()
            vfunc = node.value.func
            if isinstance(vfunc, ast.Attribute) \
                    and isinstance(vfunc.value, ast.Attribute):
                pool_attr = vfunc.value.attr
                pair = rules.RESOURCE_POOL_ATTRS.get(pool_attr)
                if pair is not None and vfunc.attr == pair[0]:
                    recv = dotted(vfunc.value)
                    out.append(Resource(
                        rid, "pool", node.targets[0].id, recv, pair[1],
                        f"{recv}.{pair[0]}() slot", node.lineno,
                        id(node)))
                    rid += 1
                    continue
        # receiver-keyed pair acquires: sel.register(...) / cache.pin(..)
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in rules.RESOURCE_METHOD_PAIRS \
                and id(node) not in with_ctx_ids:
            recv = dotted(node.func.value)
            verb = rules.RESOURCE_METHOD_PAIRS[node.func.attr]
            if recv is not None and releases_somewhere(recv, verb):
                out.append(Resource(
                    rid, "pair", None, recv, verb,
                    f"{recv}.{node.func.attr}()", node.lineno, id(node)))
                rid += 1
        # refcount pin: ent.refcount += 1
        if isinstance(node, ast.AugAssign) \
                and isinstance(node.target, ast.Attribute) \
                and node.target.attr in rules.RESOURCE_REFCOUNT_ATTRS \
                and isinstance(node.op, ast.Add):
            recv = dotted(node.target.value)
            if recv is not None and releases_somewhere(recv, "refdec"):
                out.append(Resource(
                    rid, "ref", None if recv.startswith("self.")
                    else recv.split(".")[0], recv, "refdec",
                    f"{recv}.refcount += 1", node.lineno, id(node)))
                rid += 1
    return out


class _FnAnalysis:
    def __init__(self, graph: CallGraph, info: FunctionInfo,
                 resources: List[Resource],
                 summaries: Dict[str, Set[Tuple[str, str]]]):
        self.graph = graph
        self.info = info
        self.resources = resources
        self.summaries = summaries
        self.by_node: Dict[int, Resource] = {
            r.node_id: r for r in resources}
        self.by_name: Dict[str, List[Resource]] = {}
        for r in resources:
            if r.name is not None:
                self.by_name.setdefault(r.name, []).append(r)

    # ---------------------------------------------- per-stmt classifiers

    def _released_in(self, stmt: ast.stmt, state: State) -> Set[int]:
        out: Set[int] = set()
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                verb, recv_node = node.func.attr, node.func.value
                recv_d = dotted(recv_node)
                for r in self.resources:
                    if r.rid not in state:
                        continue
                    if r.kind == "ctor" and verb == r.release_verb \
                            and isinstance(recv_node, ast.Name) \
                            and recv_node.id == r.name:
                        out.add(r.rid)
                    elif r.kind == "pair" and verb == r.release_verb \
                            and recv_d == r.recv_key:
                        out.add(r.rid)
                    elif r.kind == "pool" and verb == r.release_verb \
                            and recv_d == r.recv_key \
                            and any(isinstance(sub, ast.Name)
                                    and sub.id == r.name
                                    for a in node.args
                                    for sub in ast.walk(a)):
                        # the leased value anywhere in an argument
                        # expression counts: the page-allocator idiom
                        # frees collections (``free(shared + fresh)``),
                        # not just the bare local
                        out.add(r.rid)
                    elif r.kind == "lease" \
                            and _lease_rpc_name(node) == r.release_verb:
                        # any client object discharges a lease: the
                        # reservation id, not the receiver, keys it
                        out.add(r.rid)
                    elif r.kind == "ckpt" and verb == r.release_verb \
                            and recv_d == "self":
                        # ``self._save_state()``: obligation discharged
                        out.add(r.rid)
                # release-through-self-call (``self._drop(st)``)
                callee, _vs = self.graph.resolve_call_cached(
                    node, self.info)
                if callee is not None:
                    rel = self.summaries.get(callee, ())
                    for r in self.resources:
                        if r.rid in state \
                                and r.kind in ("pair", "ref", "lease",
                                               "ckpt") \
                                and r.recv_key is not None \
                                and r.recv_key.startswith(("self.",
                                                           "rpc:",
                                                           "ckpt:")) \
                                and (r.recv_key, r.release_verb) in rel:
                            out.add(r.rid)
            elif isinstance(node, ast.AugAssign) \
                    and isinstance(node.target, ast.Attribute) \
                    and node.target.attr in rules.RESOURCE_REFCOUNT_ATTRS \
                    and isinstance(node.op, ast.Sub):
                recv_d = dotted(node.target.value)
                for r in self.resources:
                    if r.rid in state and r.kind == "ref" \
                            and recv_d == r.recv_key:
                        out.add(r.rid)
        return out

    def _transferred_in(self, stmt: ast.stmt, state: State) -> Set[int]:
        """Ownership transfers: the resource local stored via an
        assignment value, returned, yielded, or raised."""
        exprs: List[ast.AST] = []
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            if stmt.value is not None:
                exprs.append(stmt.value)
        elif isinstance(stmt, ast.Return) and stmt.value is not None:
            exprs.append(stmt.value)
        elif isinstance(stmt, ast.Raise) and stmt.exc is not None:
            exprs.append(stmt.exc)
        elif isinstance(stmt, ast.Expr) \
                and isinstance(stmt.value, (ast.Yield, ast.YieldFrom)):
            exprs.append(stmt.value)
        out: Set[int] = set()
        for expr in exprs:
            for node in ast.walk(expr):
                if isinstance(node, ast.Name):
                    for r in self.by_name.get(node.id, ()):
                        if r.rid in state:
                            out.add(r.rid)
                elif isinstance(node, ast.Attribute):
                    d = dotted(node)
                    for r in self.resources:
                        if r.rid in state and r.recv_key is not None \
                                and d == r.recv_key:
                            out.add(r.rid)
        # Lease handoff: the lease local passed as a BARE argument to
        # any call transfers ownership (``ReplicaRecord(h, rid, sub)``
        # — the record now owns the reservation); a nested read
        # (``f(sub["chips"])``) stays a borrow.
        lease_names = {r.name: r for r in self.resources
                       if r.kind == "lease" and r.name is not None}
        if lease_names:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                args = list(node.args) + [kw.value
                                          for kw in node.keywords]
                for a in args:
                    if isinstance(a, ast.Name) and a.id in lease_names:
                        r = lease_names[a.id]
                        if r.rid in state:
                            out.add(r.rid)
        return out

    def _acquired_in(self, stmt: ast.stmt) -> Set[int]:
        out: Set[int] = set()
        for node in ast.walk(stmt):
            r = self.by_node.get(id(node))
            if r is not None:
                out.add(r.rid)
        return out

    @staticmethod
    def _may_raise(stmt: ast.stmt) -> bool:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Call, ast.Subscript, ast.BinOp,
                                 ast.Raise, ast.Assert)):
                return True
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                # attribute LOADS can raise (missing attr); a plain
                # store target (``self.sock = sock``) cannot, short of
                # a property — treating it as raising would turn every
                # ownership-transferring assignment into a leak edge
                return True
        return False

    # ------------------------------------------------------ interpreter

    def run(self) -> Outcomes:
        # Checkpoint obligations are live from the first statement; all
        # other resources enter the state at their acquire site.
        entry = frozenset(r.rid for r in self.resources
                          if r.kind == "ckpt")
        return self._block(list(self.info.node.body), entry)

    def _block(self, stmts: List[ast.stmt], state: State) -> Outcomes:
        out: Outcomes = {k: set() for k in _EXITS}
        for stmt in stmts:
            res = self._stmt(stmt, state)
            for kind in ("return", "raise", "break", "continue"):
                out[kind] |= res[kind]
            falls = res["fall"]
            if not falls:
                return out  # unreachable continuation
            # merge fall states (sets of live-resource sets)
            state = frozenset().union(*[s for s, _ in falls]) \
                if len(falls) > 1 else next(iter(falls))[0]
            # keep path distinction cheap: union over-approximates
            # "live on some path", which is what leak detection needs
        out["fall"].add((state, stmts[-1].lineno if stmts else 0))
        return out

    def _stmt(self, stmt: ast.stmt, state: State) -> Outcomes:
        out: Outcomes = {k: set() for k in _EXITS}
        line = stmt.lineno

        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            out["fall"].add((state, line))
            return out

        if isinstance(stmt, ast.If):
            if self._may_raise_expr(stmt.test):
                out["raise"].add((state, line))
            # ``if x is None:`` / ``if x is not None:`` — the branch in
            # which x is None cannot hold the resource bound to x (the
            # failed-acquire guard idiom: ``sub = reserve(); if sub is
            # None: return False``), so prune it there.
            none_name, when_none = self._none_test(stmt.test)
            branch_states = [state, state]
            if none_name is not None:
                dead = frozenset(r.rid for r in self.resources
                                 if r.name == none_name)
                branch_states[0 if when_none else 1] = state - dead
            for branch, bstate in zip((stmt.body, stmt.orelse),
                                      branch_states):
                res = self._block(branch, bstate) if branch else \
                    {k: (set() if k != "fall" else {(bstate, line)})
                     for k in _EXITS}
                for k in _EXITS:
                    out[k] |= res[k]
            return out

        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            test = stmt.test if isinstance(stmt, ast.While) else stmt.iter
            if self._may_raise_expr(test):
                out["raise"].add((state, line))
            body = self._block(stmt.body, state)
            # zero iterations: fall with entry state; >=1: body outcomes
            out["return"] |= body["return"]
            out["raise"] |= body["raise"]
            # A receiver-keyed registration that survives a whole loop
            # iteration is settled object state (the reactor accept loop
            # registering conn after conn), not a leak-in-flight: scope
            # it to the iteration. Mid-iteration raises (the PR-1 bug
            # class) were already recorded above with it live.
            iter_pairs = frozenset(
                r.rid for r in self.resources
                if r.kind == "pair" and any(
                    id(n) == r.node_id for n in ast.walk(stmt)))
            falls = {(state, line)}
            falls |= {(s - iter_pairs, ln) for s, ln in body["fall"]}
            falls |= {(s - iter_pairs, ln) for s, ln in body["break"]}
            falls |= {(s - iter_pairs, ln) for s, ln in body["continue"]}
            if stmt.orelse:
                merged: Set[Tuple[State, int]] = set()
                for s, _ln in falls:
                    res = self._block(stmt.orelse, s)
                    for k in ("return", "raise", "break", "continue"):
                        out[k] |= res[k]
                    merged |= res["fall"]
                falls = merged
            out["fall"] |= falls
            return out

        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            if any(self._may_raise_expr(i.context_expr)
                   for i in stmt.items):
                out["raise"].add((state, line))
            res = self._block(stmt.body, state)
            for k in _EXITS:
                out[k] |= res[k]
            return out

        if isinstance(stmt, ast.Try):
            body = self._block(stmt.body, state)
            out["return"] |= body["return"]
            out["break"] |= body["break"]
            out["continue"] |= body["continue"]
            falls = set(body["fall"])
            raises = set(body["raise"])
            for h in stmt.handlers:
                for s, _ln in raises or {(state, line)}:
                    res = self._block(h.body, s)
                    for k in ("return", "break", "continue"):
                        out[k] |= res[k]
                    falls |= res["fall"]
                    out["raise"] |= res["raise"]
            # Handlers are assumed to catch (optimistic): a handler that
            # neither releases nor re-raises still leaves the resource
            # live in its fall state, so the leak is reported at the
            # function's real exits instead of at a hypothetical
            # uncaught-exception edge. Pure try/finally keeps the edge.
            if not stmt.handlers:
                out["raise"] |= raises
            if stmt.orelse:
                merged: Set[Tuple[State, int]] = set()
                for s, _ln in set(body["fall"]) or {(state, line)}:
                    res = self._block(stmt.orelse, s)
                    for k in ("return", "raise", "break", "continue"):
                        out[k] |= res[k]
                    merged |= res["fall"]
                falls = (falls - set(body["fall"])) | merged
            if stmt.finalbody:
                kills = self._finally_kills(stmt.finalbody)
                fres = self._block(stmt.finalbody,
                                   frozenset().union(
                                       *[s for s, _ in falls])
                                   if falls else state)
                out["raise"] |= fres["raise"]
                out["return"] |= fres["return"]

                def k(pairs):
                    return {(s - kills, ln) for s, ln in pairs}
                for kind in _EXITS:
                    out[kind] = k(out[kind])
                falls = k(falls)
            out["fall"] |= falls
            return out

        if isinstance(stmt, ast.Return):
            # transfer BEFORE the raise edge: ``return Wrap(res)`` whose
            # constructor raises is assumed to have taken the resource,
            # same optimism as the assignment form below
            s = state - self._transferred_in(stmt, state)
            if stmt.value is not None and self._may_raise_expr(stmt.value):
                out["raise"].add((s, line))
            out["return"].add((s, line))
            return out

        if isinstance(stmt, ast.Raise):
            s = state - self._transferred_in(stmt, state)
            out["raise"].add((s, line))
            return out

        if isinstance(stmt, ast.Break):
            out["break"].add((state, line))
            return out

        if isinstance(stmt, ast.Continue):
            out["continue"].add((state, line))
            return out

        # simple statement: releases AND transfers happen "before" the
        # raise edge (a close() that itself raises has still torn the
        # resource down; a wrapping constructor that raises is assumed
        # to have taken the resource — optimistic, but the noisy
        # alternative flags every ``st = _Conn(sock)``). Acquires land
        # only on the fall edge: an acquire that raises acquired
        # nothing.
        s = state - self._released_in(stmt, state)
        s = s - self._transferred_in(stmt, s)
        if self._may_raise(stmt):
            out["raise"].add((s, line))
        s = s | self._acquired_in(stmt)
        out["fall"].add((s, line))
        return out

    def _finally_kills(self, finalbody: List[ast.stmt]) -> Set[int]:
        all_ids: State = frozenset(r.rid for r in self.resources)
        kills: Set[int] = set()
        for stmt in finalbody:
            kills |= self._released_in(stmt, all_ids)
            kills |= self._transferred_in(stmt, all_ids)
            # ``for f in (stdout, stderr): f.close()`` — a loop variable
            # ranging over resource locals releases each of them.
            for node in ast.walk(stmt):
                if not (isinstance(node, ast.For)
                        and isinstance(node.target, ast.Name)
                        and isinstance(node.iter, (ast.Tuple, ast.List))):
                    continue
                names = {el.id for el in node.iter.elts
                         if isinstance(el, ast.Name)}
                verbs = {sub.func.attr for sub in ast.walk(node)
                         if isinstance(sub, ast.Call)
                         and isinstance(sub.func, ast.Attribute)
                         and isinstance(sub.func.value, ast.Name)
                         and sub.func.value.id == node.target.id}
                for r in self.resources:
                    if r.name in names and r.release_verb in verbs:
                        kills.add(r.rid)
        return kills

    @staticmethod
    def _none_test(test: ast.AST) -> Tuple[Optional[str], bool]:
        """-> (name, True) for ``name is None``, (name, False) for
        ``name is not None``, else (None, False)."""
        if isinstance(test, ast.Compare) and len(test.ops) == 1 \
                and isinstance(test.left, ast.Name) \
                and len(test.comparators) == 1 \
                and isinstance(test.comparators[0], ast.Constant) \
                and test.comparators[0].value is None:
            if isinstance(test.ops[0], ast.Is):
                return test.left.id, True
            if isinstance(test.ops[0], ast.IsNot):
                return test.left.id, False
        return None, False

    def _may_raise_expr(self, expr: Optional[ast.AST]) -> bool:
        if expr is None:
            return False
        for node in ast.walk(expr):
            if isinstance(node, (ast.Call, ast.Subscript, ast.BinOp,
                                 ast.Attribute)):
                return True
        return False


def _candidate_fqns(graph: CallGraph) -> Set[str]:
    """Functions that can possibly acquire a tracked resource, from the
    calls-by-tail side index — every other function is skipped whole."""
    cands: Set[str] = set()
    ctor_tails = {d.split(".")[-1] for d in rules.RESOURCE_CTOR_DOTTED}
    for tail in ctor_tails | set(rules.RESOURCE_METHOD_PAIRS):
        for _node, info in graph.calls_by_tail.get(tail, ()):
            cands.add(info.fqn)
    for pool_attr, (acq_verb, _rel) in rules.RESOURCE_POOL_ATTRS.items():
        for node, info in graph.calls_by_tail.get(acq_verb, ()):
            if isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Attribute) \
                    and node.func.value.attr == pool_attr:
                cands.add(info.fqn)
    for node, info in graph.attr_augassigns:
        if node.target.attr in rules.RESOURCE_REFCOUNT_ATTRS \
                and isinstance(node.op, ast.Add):
            cands.add(info.fqn)
    for tail in tuple(rules.RPC_LEASE_VERBS) + tuple(
            rules.RPC_LEASE_PAIRS):
        for node, info in graph.calls_by_tail.get(tail, ()):
            if _lease_rpc_name(node) in rules.RPC_LEASE_PAIRS:
                cands.add(info.fqn)
    for info in graph.functions.values():
        if _ckpt_entry(info) is not None:
            cands.add(info.fqn)
    return cands


def check(graph: CallGraph, emit_files=None) -> List[Finding]:
    summaries = _release_summaries(graph)
    findings: List[Finding] = []
    for fqn in sorted(_candidate_fqns(graph)):
        info = graph.functions[fqn]
        if emit_files is not None \
                and info.file.relpath not in emit_files:
            continue  # per-function analysis; summaries stay global
        if any(isinstance(n, (ast.Yield, ast.YieldFrom))
               for n in _walk_no_nested(info.node)):
            continue  # generators suspend: path exits are meaningless
        resources = _collect_resources(graph, info, summaries)
        if not resources:
            continue
        analysis = _FnAnalysis(graph, info, resources, summaries)
        outcomes = analysis.run()
        by_rid = {r.rid: r for r in resources}
        leaks: Dict[int, Tuple[str, int]] = {}
        for kind in ("fall", "return", "raise"):
            for s, ln in outcomes[kind]:
                for rid in s:
                    # receiver-keyed registrations (and topology leases)
                    # live at a NORMAL exit are the design (a long-lived
                    # registration / record-owned reservation); only an
                    # exception escaping between acquire and release is
                    # a leak for those.
                    if by_rid[rid].kind in ("pair", "lease") \
                            and kind != "raise":
                        continue
                    # checkpoint obligations are the INVERSE: normal
                    # exits must have saved; an escaping exception is
                    # exempt (the handler failed — there may be nothing
                    # durable to record).
                    if by_rid[rid].kind == "ckpt" and kind == "raise":
                        continue
                    prev = leaks.get(rid)
                    if prev is None or ln < prev[1]:
                        label = {"fall": "fall-through",
                                 "return": "return",
                                 "raise": "escaping exception"}[kind]
                        leaks[rid] = (label, ln)
        for r in resources:
            hit = leaks.get(r.rid)
            if hit is None:
                continue
            kind_label, ln = hit
            if r.kind == "ckpt":
                findings.append(Finding(
                    rule=rules.CHECKPOINT_MISSING,
                    path=info.file.relpath, line=r.line,
                    symbol=info.qualname,
                    message=f"{r.label}: this state-mutating handler "
                            f"can exit via {kind_label} (line {ln}) "
                            f"without reaching {r.release_verb}() — "
                            f"the mutation is invisible to a restarted "
                            f"controller (it would replay the previous "
                            f"checkpoint)"))
                continue
            findings.append(Finding(
                rule=rules.RESOURCE_LEAK, path=info.file.relpath,
                line=r.line, symbol=info.qualname,
                message=f"{r.label} acquired here is still live when "
                        f"the function exits via {kind_label} "
                        f"(line {ln}) — release it "
                        f"({r.release_verb}) in a finally, or transfer "
                        f"ownership"))
    return findings
