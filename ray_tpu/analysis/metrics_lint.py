"""Metrics family (#10): name collisions and label cardinality.

**metrics-name-collision** — one metric name, one definition. The
metrics registry keys entries by (name, tags); two call sites
registering the SAME name as different KINDS (Counter vs Histogram) or
with different histogram BUCKET grids silently produce entries that can
never be merged — the controller aggregation, ``slo_summary`` and the
Prometheus text all key by name, so the collision corrupts every
downstream percentile instead of failing anywhere visible. This check
makes it fail at ``make lint``.

Collected package-wide: constructor calls of ``Counter`` / ``Gauge`` /
``Histogram`` that resolve (via the module's imports) to
``ray_tpu.util.metrics`` — ``collections.Counter`` and friends are not
confused — whose first argument is a literal string. The definition
signature is (kind, boundaries-literal); the first site wins and every
later disagreeing site is flagged.

**metrics-label-cardinality** — label VALUES must be bounded. A tag
like ``{"request": request_id}`` creates one registry series per
request: the series never merge (each key is unique), the per-process
snapshot grows until the ``metrics_max_series`` cap starts dropping
BOUNDED series, and every snapshot push carries the garbage. Flagged
at record call sites (``.inc/.set/.observe/.observe_many(...,
tags={...})`` and ``set_default_tags({...})``): any label-value
expression containing an id-shaped terminal name (``*_id``, ``oid``,
``uuid``, …) or an id-producing call (``.hex()``, ``uuid4()``). Label
values that are genuinely bounded ids (node ids: series die with the
node) carry a pragma with the justification.

**Flight-recorder events** (PR 15) go through the same two checks at
``flightrec.record("<name>", **attrs)`` sites (import-resolved to
``ray_tpu.util.flightrec`` — any other ``record`` is never confused):
one event name, one ATTR-KEY SCHEMA (``doctor.post_mortem`` merges
events by name; a site recording the same name with different keys
silently breaks every grouping — flagged as metrics-name-collision),
and id-shaped attr values flagged as metrics-label-cardinality —
bounded schedule ints (``rules.FLIGHTREC_BOUNDED_ATTRS``: step, mb,
stage, epoch, …) are exempt, and genuinely-bounded subject ids (gang
ids die with the gang) carry the same justification pragma as metric
labels.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ray_tpu.analysis import rules
from ray_tpu.analysis.core import Finding, Project, qualname_of

_METRIC_CLASSES = {"Counter", "Gauge", "Histogram"}
_METRICS_MODULE = "ray_tpu.util.metrics"


def _metric_aliases(tree: ast.AST) -> Tuple[Dict[str, str], set]:
    """(direct aliases: local name -> metric class) and (module
    aliases: local names bound to ray_tpu.util.metrics itself)."""
    direct: Dict[str, str] = {}
    mod_aliases: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module == _METRICS_MODULE:
                for a in node.names:
                    if a.name in _METRIC_CLASSES:
                        direct[a.asname or a.name] = a.name
            elif node.module == "ray_tpu.util":
                for a in node.names:
                    if a.name == "metrics":
                        mod_aliases.add(a.asname or "metrics")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == _METRICS_MODULE:
                    mod_aliases.add(a.asname or "ray_tpu")
    return direct, mod_aliases


def _resolve_metric_class(call: ast.Call, direct: Dict[str, str],
                          mod_aliases: set) -> Optional[str]:
    fn = call.func
    if isinstance(fn, ast.Name):
        return direct.get(fn.id)
    if (isinstance(fn, ast.Attribute) and fn.attr in _METRIC_CLASSES
            and isinstance(fn.value, ast.Name)
            and fn.value.id in mod_aliases):
        return fn.attr
    return None


def _boundaries_literal(call: ast.Call) -> Optional[str]:
    """Canonical text of the ``boundaries`` argument (kwarg or the
    Histogram signature's 3rd positional). None = registry default.
    Compared as AST dumps: a NON-literal expression only matches
    itself spelled identically, which is exactly the conservative
    behavior wanted (same constant name = same grid)."""
    for kw in call.keywords:
        if kw.arg == "boundaries":
            return ast.dump(kw.value)
    if len(call.args) >= 3:
        return ast.dump(call.args[2])
    return None


def _is_id_shaped(expr: ast.AST) -> Optional[str]:
    """The sub-expression that makes a label value unbounded, rendered
    for the message — or None when the value looks bounded."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if name in rules.METRICS_ID_CALLS:
                return f"{name}() call"
        elif isinstance(node, (ast.Name, ast.Attribute)):
            term = node.id if isinstance(node, ast.Name) else node.attr
            if (term in rules.METRICS_ID_NAMES
                    or term.endswith(rules.METRICS_ID_SUFFIX)):
                return f"identifier {term!r}"
    return None


def _tags_dict(call: ast.Call, method: str) -> Optional[ast.Dict]:
    """The tags dict literal of a metric-record call, if present."""
    for kw in call.keywords:
        if kw.arg == "tags" and isinstance(kw.value, ast.Dict):
            return kw.value
    idx = 0 if method == "set_default_tags" else 1
    if len(call.args) > idx and isinstance(call.args[idx], ast.Dict):
        return call.args[idx]
    return None


def _check_cardinality(project: Project, emit_files=None) -> List[Finding]:
    findings: List[Finding] = []
    for f in sorted(project.files, key=lambda s: s.relpath):
        if emit_files is not None and f.relpath not in emit_files:
            continue
        stack: List[ast.AST] = []

        def visit(node: ast.AST) -> None:
            is_scope = isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef))
            if is_scope:
                stack.append(node)
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_scope:
                stack.pop()
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in rules.METRICS_RECORD_METHODS):
                return
            tags = _tags_dict(node, node.func.attr)
            if tags is None:
                return
            for key, value in zip(tags.keys, tags.values):
                if not (isinstance(key, ast.Constant)
                        and isinstance(key.value, str)):
                    continue  # **splat merges checked at their own site
                if isinstance(value, ast.Constant):
                    continue
                why = _is_id_shaped(value)
                if why is None:
                    continue
                findings.append(Finding(
                    rule=rules.METRICS_CARDINALITY, path=f.relpath,
                    line=node.lineno, symbol=qualname_of(stack),
                    message=(f"label {key.value!r} takes an id-shaped "
                             f"value ({why}): one registry series per "
                             f"id never merges and floods every "
                             f"snapshot push — use a bounded label "
                             f"(role/outcome/deployment) or pragma "
                             f"with the bound's justification")))

        visit(f.tree)
    return findings


def _flightrec_aliases(tree: ast.AST) -> Tuple[set, set]:
    """(direct names bound to flightrec.record) and (local names bound
    to the ray_tpu.util.flightrec module itself)."""
    direct: set = set()
    mod_aliases: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module == rules.FLIGHTREC_MODULE:
                for a in node.names:
                    if a.name in rules.FLIGHTREC_RECORD_FUNCS:
                        direct.add(a.asname or a.name)
            elif node.module == "ray_tpu.util":
                for a in node.names:
                    if a.name == "flightrec":
                        mod_aliases.add(a.asname or "flightrec")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == rules.FLIGHTREC_MODULE:
                    mod_aliases.add(a.asname or "ray_tpu")
    return direct, mod_aliases


def _is_flightrec_record(call: ast.Call, direct: set,
                         mod_aliases: set) -> bool:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id in direct
    return (isinstance(fn, ast.Attribute)
            and fn.attr in rules.FLIGHTREC_RECORD_FUNCS
            and isinstance(fn.value, ast.Name)
            and fn.value.id in mod_aliases)


def _check_flightrec(project: Project, emit_files=None) -> List[Finding]:
    """Flight-recorder event discipline: collect every literal-name
    ``record()`` site package-wide (schema = sorted attr keys; the
    first site wins), then flag schema collisions and id-shaped attr
    values — the family-#10 checks applied to the event catalog."""
    sites: Dict[str, List[dict]] = {}
    card: List[Finding] = []
    for f in sorted(project.files, key=lambda s: s.relpath):
        direct, mod_aliases = _flightrec_aliases(f.tree)
        if not direct and not mod_aliases:
            continue
        stack: List[ast.AST] = []

        def visit(node: ast.AST) -> None:
            is_scope = isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef))
            if is_scope:
                stack.append(node)
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_scope:
                stack.pop()
            if not (isinstance(node, ast.Call)
                    and _is_flightrec_record(node, direct, mod_aliases)
                    and node.args):
                return
            name_arg = node.args[0]
            if not (isinstance(name_arg, ast.Constant)
                    and isinstance(name_arg.value, str)):
                return
            keys = tuple(sorted(kw.arg for kw in node.keywords
                                if kw.arg is not None))
            sites.setdefault(name_arg.value, []).append({
                "relpath": f.relpath, "line": node.lineno,
                "symbol": qualname_of(stack), "keys": keys})
            if emit_files is not None and f.relpath not in emit_files:
                return
            for kw in node.keywords:
                if (kw.arg is None
                        or kw.arg in rules.FLIGHTREC_BOUNDED_ATTRS
                        or isinstance(kw.value, ast.Constant)):
                    continue
                why = _is_id_shaped(kw.value)
                if why is None:
                    continue
                card.append(Finding(
                    rule=rules.METRICS_CARDINALITY, path=f.relpath,
                    line=node.lineno, symbol=qualname_of(stack),
                    message=(f"flight-recorder event "
                             f"{name_arg.value!r} attr {kw.arg!r} "
                             f"takes an id-shaped value ({why}): "
                             f"per-id events are a metric trying to "
                             f"be born — use a bounded attr, or "
                             f"pragma with the bound's justification "
                             f"(gang/pipeline ids die with their "
                             f"subject)")))

        visit(f.tree)

    findings: List[Finding] = []
    for name, regs in sites.items():
        first = regs[0]
        for site in regs[1:]:
            if site["keys"] == first["keys"]:
                continue
            if (emit_files is not None
                    and site["relpath"] not in emit_files):
                continue
            findings.append(Finding(
                rule=rules.METRICS_COLLISION, path=site["relpath"],
                line=site["line"], symbol=site["symbol"],
                message=(f"flight-recorder event {name!r} recorded "
                         f"with attr keys {list(site['keys'])} here "
                         f"but {list(first['keys'])} at "
                         f"{first['relpath']}:{first['line']} — one "
                         f"event name, one schema (the post-mortem "
                         f"merges events by name)")))
    findings.extend(card)
    return findings


def check_project(project: Project, emit_files=None) -> List[Finding]:
    # First pass: every literal-name registration in the package, in
    # deterministic file order, so "first site wins" is stable.
    sites: Dict[str, List[dict]] = {}
    for f in sorted(project.files, key=lambda s: s.relpath):
        direct, mod_aliases = _metric_aliases(f.tree)
        if not direct and not mod_aliases:
            continue
        stack: List[ast.AST] = []

        def visit(node: ast.AST) -> None:
            is_scope = isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef))
            if is_scope:
                stack.append(node)
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_scope:
                stack.pop()
            if not isinstance(node, ast.Call):
                return
            cls = _resolve_metric_class(node, direct, mod_aliases)
            if cls is None or not node.args:
                return
            name_arg = node.args[0]
            if not (isinstance(name_arg, ast.Constant)
                    and isinstance(name_arg.value, str)):
                return
            sites.setdefault(name_arg.value, []).append({
                "relpath": f.relpath, "line": node.lineno,
                "symbol": qualname_of(stack), "cls": cls,
                "boundaries": (_boundaries_literal(node)
                               if cls == "Histogram" else None),
            })

        visit(f.tree)

    findings: List[Finding] = []
    for name, regs in sites.items():
        first = regs[0]
        for site in regs[1:]:
            if site["cls"] != first["cls"]:
                msg = (f"metric {name!r} registered as {site['cls']} "
                       f"here but as {first['cls']} at "
                       f"{first['relpath']}:{first['line']} — one name, "
                       f"one kind")
            elif (site["cls"] == "Histogram"
                  and site["boundaries"] != first["boundaries"]):
                msg = (f"histogram {name!r} registered with different "
                       f"bucket boundaries than "
                       f"{first['relpath']}:{first['line']} — entries "
                       f"with mismatched grids can never be merged")
            else:
                continue
            if (emit_files is not None
                    and site["relpath"] not in emit_files):
                continue
            findings.append(Finding(
                rule=rules.METRICS_COLLISION, path=site["relpath"],
                line=site["line"], symbol=site["symbol"], message=msg))
    findings.extend(_check_cardinality(project, emit_files))
    findings.extend(_check_flightrec(project, emit_files))
    return findings
