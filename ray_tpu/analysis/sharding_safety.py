"""sharding-safety: static GSPMD sharding / mesh-scope checking.

PR 7 made the serving plane's correctness rest on hand-maintained
sharding invariants: the decode rule table never partitions a
contraction dim, every row-parallel reduction is preceded by a
``constrain`` anchor (gather-then-contract, so sharded logits stay
BIT-EXACT vs the single-chip program), and every sharded program is
traced under an ``axis_rules`` scope with pinned shardings. Runtime
tests police those invariants only on the mesh shapes they happen to
trace; this checker evaluates them *statically*, against the rule
tables themselves — an edit that partitions a contraction dim in
``DECODE_RULES`` is caught without importing jax. Four rules:

* sharding-partitioned-contraction — an einsum/dot/matmul site whose
  contracted dim carries a logical axis that a bit-exactness table
  (``DECODE_RULES``) maps to a mesh axis. Operand axes resolve two
  ways: activation locals flow from their nearest preceding
  ``constrain(x, (...axes...))`` assignment; weight operands
  (``layer["wo"]``-style literal subscripts) resolve through the
  ``param_axes``/``decode_param_axes`` tables (decode overrides win —
  that is where ``wo``/``w_down`` are re-bound to replicated).
  Unresolvable operands are skipped (conservative silence).
* sharding-missing-anchor — a reduction against a ROW-PARALLEL weight
  (derived from the tables: decode axes fully replicated while the
  train axes shard a dim) whose activation operand does not flow from a
  ``constrain`` anchor. Without the anchor, propagation shards the
  contracted dim upstream (heads/mlp over "model") and XLA emits a
  partial-sum psum — numerically fine, bit-exactness broken.
* sharding-unpinned-mesh-call — a jit-family call inside a mesh scope
  (a ``with axis_rules(...)`` block, or the argument of a
  ``*_mesh_scoped`` wrapper) carrying no ``in_shardings``/
  ``out_shardings`` (a ``**kwargs`` splat counts as unknown and is not
  flagged), or a ``device_put`` inside a scope with no placement
  argument — unpinned programs let XLA re-place committed state.
* sharding-unscoped-trace — a jit call WITH explicit sharding kwargs
  whose wrapped callable (transitively) hits a ``constrain`` site, yet
  the jit is neither inside an ``axis_rules`` block, nor passed through
  a mesh-scope wrapper, nor does the wrapped callable open the scope
  itself (the train-step idiom: ``with axis_rules(...)`` inside the
  traced body). Out of scope, ``constrain`` is a silent no-op — the
  program compiles, unsharded, and the invariant evaporates.

All tables are parsed from the AST (``ast.literal_eval`` on the dict /
tuple literals); nothing here imports jax or the model code.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ray_tpu.analysis import rules
from ray_tpu.analysis.callgraph import (CallGraph, FunctionInfo, dotted,
                                        _walk_no_nested)
from ray_tpu.analysis.core import Finding, Project

Axes = Tuple[Optional[str], ...]


# ------------------------------------------------------- table parsing

def _literal_axes(node: ast.AST) -> Optional[Axes]:
    """A literal tuple of axis names (str | None | nested tuple is
    flattened to its first element for matching purposes), else None."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    try:
        val = ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None
    out = []
    for el in val:
        if el is None or isinstance(el, str):
            out.append(el)
        else:
            return None
    return tuple(out)


def load_rule_tables(project: Project
                     ) -> Dict[str, Tuple[Dict[str, object], str,
                                          Dict[str, int]]]:
    """table name -> (axis -> mesh-axis-or-None, relpath, axis lines)
    for every module-level ``NAME: Rules = {...literal...}`` in the
    sharding-rules module."""
    f = project.by_module.get(rules.SHARDING_RULES_MODULE)
    out: Dict[str, Tuple[Dict[str, object], str, Dict[str, int]]] = {}
    if f is None:
        return out
    wanted = set(rules.SHARDING_BITEXACT_TABLES) | {
        rules.SHARDING_TRAIN_TABLE}
    for node in f.tree.body:
        tgt = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            tgt, val = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.value is not None:
            tgt, val = node.target.id, node.value
        if tgt is None or tgt not in wanted \
                or not isinstance(val, ast.Dict):
            continue
        table: Dict[str, object] = {}
        lines: Dict[str, int] = {}
        for k, v in zip(val.keys, val.values):
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)):
                continue
            try:
                table[k.value] = ast.literal_eval(v)
            except (ValueError, SyntaxError):
                continue
            lines[k.value] = k.lineno
        out[tgt] = (table, f.relpath, lines)
    return out


def load_param_axes(project: Project) -> Tuple[Dict[str, Axes],
                                               Dict[str, Axes]]:
    """(train weight axes, decode weight axes) keyed by weight name,
    extracted from the literal tuple bindings inside the param-axes
    functions (``layers["wo"] = (...)`` / ``{"wo": (...)}`` forms).
    The decode map is the train map with the decode function's
    re-bindings applied on top."""
    f = project.by_module.get(rules.SHARDING_PARAM_AXES_MODULE)
    train: Dict[str, Axes] = {}
    decode_over: Dict[str, Axes] = {}
    if f is None:
        return train, dict(train)

    def harvest(fn: ast.AST, into: Dict[str, Axes]) -> None:
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Subscript) \
                    and isinstance(node.targets[0].slice, ast.Constant) \
                    and isinstance(node.targets[0].slice.value, str):
                axes = _literal_axes(node.value)
                if axes is not None:
                    into[node.targets[0].slice.value] = axes
            elif isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str):
                        axes = _literal_axes(v)
                        if axes is not None:
                            into.setdefault(k.value, axes)

    for node in ast.walk(f.tree):
        if isinstance(node, ast.FunctionDef):
            if node.name in rules.SHARDING_PARAM_AXES_FUNCS:
                harvest(node, train)
            elif node.name in rules.SHARDING_DECODE_AXES_FUNCS:
                harvest(node, decode_over)
    decode = dict(train)
    decode.update(decode_over)
    return train, decode


def row_parallel_weights(train: Dict[str, Axes], decode: Dict[str, Axes],
                         train_table: Dict[str, object]) -> Set[str]:
    """Weight names whose decode axes are fully replicated while their
    train axes shard some dim — the Megatron row-parallel pair
    (``wo``/``w_down``): their inputs are CONTRACTED, so the sharded
    serving path keeps them replicated and relies on a pre-contraction
    ``constrain`` anchor instead."""
    out: Set[str] = set()
    for name, d_axes in decode.items():
        t_axes = train.get(name)
        if t_axes is None or t_axes == d_axes:
            continue
        body = [a for a in d_axes if a != "layers"]
        if any(a is not None for a in body):
            continue  # decode still shards it: not the replicated pair
        if any(a is not None and train_table.get(a) is not None
               for a in t_axes):
            out.add(name)
    return out


# ------------------------------------------------- operand resolution

def _peel(expr: ast.AST) -> ast.AST:
    """Strip ``.astype(...)`` wrappers: they change dtype, not axes."""
    while isinstance(expr, ast.Call) \
            and isinstance(expr.func, ast.Attribute) \
            and expr.func.attr == "astype":
        expr = expr.func.value
    return expr


def _is_constrain(call: ast.Call) -> bool:
    d = dotted(call.func)
    return d is not None \
        and d.split(".")[-1] in rules.SHARDING_CONSTRAIN_FUNCS


def _constrain_axes(call: ast.Call) -> Optional[Axes]:
    if len(call.args) >= 2:
        return _literal_axes(call.args[1])
    return None


class _AxisEnv:
    """Per-function map of local names to logical-axes tuples, flowing
    from ``x = constrain(x, (...axes...))`` assignments. A later
    reassignment from anything else kills the binding (lexical order by
    line — the model code is straight-line enough for that)."""

    def __init__(self, info: FunctionInfo):
        # name -> [(lineno, axes-or-None)]
        self.defs: Dict[str, List[Tuple[int, Optional[Axes]]]] = {}
        for node in _walk_no_nested(info.node):
            if isinstance(node, ast.Assign):
                axes = None
                val = _peel(node.value)
                if isinstance(val, ast.Call) and _is_constrain(val):
                    axes = _constrain_axes(val)
                for tgt in node.targets:
                    for sub in ast.walk(tgt):
                        if isinstance(sub, ast.Name):
                            one = axes if isinstance(tgt, ast.Name) \
                                else None
                            self.defs.setdefault(sub.id, []).append(
                                (node.lineno, one))
            elif isinstance(node, ast.AugAssign) \
                    and isinstance(node.target, ast.Name):
                self.defs.setdefault(node.target.id, []).append(
                    (node.lineno, None))
        for rows in self.defs.values():
            rows.sort()

    def axes_at(self, name: str, line: int) -> Optional[Axes]:
        best: Optional[Axes] = None
        seen = False
        for ln, axes in self.defs.get(name, ()):
            if ln >= line:
                break
            best, seen = axes, True
        return best if seen else None


def _operand_axes(expr: ast.AST, line: int, env: _AxisEnv,
                  weight_axes: Dict[str, Axes]
                  ) -> Tuple[Optional[Axes], Optional[str]]:
    """-> (axes or None, weight name if the operand is a weight)."""
    expr = _peel(expr)
    if isinstance(expr, ast.Call) and _is_constrain(expr):
        return _constrain_axes(expr), None
    if isinstance(expr, ast.Subscript) \
            and isinstance(expr.slice, ast.Constant) \
            and isinstance(expr.slice.value, str):
        name = expr.slice.value
        return weight_axes.get(name), name
    if isinstance(expr, ast.Name):
        return env.axes_at(expr.id, line), None
    return None, None


def _align(letters: str, axes: Axes) -> Optional[Dict[str, Optional[str]]]:
    """Map einsum subscript letters to logical axes. Inside a scanned
    layer body the leading ``layers`` axis is consumed, so a weight
    whose axes tuple is one longer than its subscript drops it."""
    if "." in letters:
        return None
    if len(letters) == len(axes):
        pairs = zip(letters, axes)
    elif len(letters) == len(axes) - 1 and axes and axes[0] == "layers":
        pairs = zip(letters, axes[1:])
    else:
        return None
    return {letter: ax for letter, ax in pairs}


# ------------------------------------------------------ rule 1 & 2

def _check_contractions(graph: CallGraph, findings: List[Finding],
                        bitexact: Dict[str, Tuple[Dict[str, object], str,
                                                  Dict[str, int]]],
                        weight_axes: Dict[str, Axes],
                        row_parallel: Set[str],
                        emit_files) -> None:
    scoped = [info for info in graph.functions.values()
              if info.file.relpath.startswith(
                  rules.SHARDING_SCOPE_PREFIXES)]
    for info in scoped:
        if emit_files is not None \
                and info.file.relpath not in emit_files:
            continue
        env: Optional[_AxisEnv] = None
        for node in _walk_no_nested(info.node):
            ops: List[Tuple[ast.AST, Optional[str]]] = []
            contracted: Sequence[str] = ()
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                tail = d.split(".")[-1] if d else None
                if tail in rules.SHARDING_CONTRACT_FUNCS \
                        and len(node.args) >= 3 \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str) \
                        and "->" in node.args[0].value:
                    spec = node.args[0].value.replace(" ", "")
                    ins, _, out_sub = spec.partition("->")
                    subs = ins.split(",")
                    if len(subs) != len(node.args) - 1:
                        continue
                    contracted = sorted(
                        {c for s in subs for c in s if c.isalpha()}
                        - set(out_sub))
                    ops = list(zip(node.args[1:], subs))
                elif tail in rules.SHARDING_MATMUL_FUNCS \
                        and "." in (d or "") and len(node.args) == 2:
                    ops = [(node.args[0], "@L"), (node.args[1], "@R")]
                    contracted = ("@k",)
            elif isinstance(node, ast.BinOp) \
                    and isinstance(node.op, ast.MatMult):
                ops = [(node.left, "@L"), (node.right, "@R")]
                contracted = ("@k",)
            if not ops or not contracted:
                continue
            if env is None:
                env = _AxisEnv(info)

            resolved: List[Tuple[Dict[str, Optional[str]],
                                 Optional[str]]] = []
            unresolved_act = False
            weight_hits: List[str] = []
            for expr, sub in ops:
                axes, wname = _operand_axes(expr, node.lineno, env,
                                            weight_axes)
                if wname is not None and wname in row_parallel:
                    weight_hits.append(wname)
                if axes is None:
                    if wname is None:
                        unresolved_act = True
                    continue
                if sub in ("@L", "@R"):
                    # matmul: contraction is left[-1] / right[0] (2-D)
                    # or right[-2] (batched) — map the single "@k" slot.
                    k_ax = axes[-1] if sub == "@L" else (
                        axes[0] if len(axes) == 2 else axes[-2])
                    resolved.append(({"@k": k_ax}, wname))
                    continue
                mapping = _align(sub, axes)
                if mapping is not None:
                    resolved.append((mapping, wname))

            # rule 1: a contracted dim carrying a partitioned axis
            flagged_axes: Set[str] = set()
            for mapping, _w in resolved:
                for letter in contracted:
                    ax = mapping.get(letter)
                    if ax is None or ax in flagged_axes:
                        continue
                    for tname in rules.SHARDING_BITEXACT_TABLES:
                        table, tpath, tlines = bitexact.get(
                            tname, ({}, "", {}))
                        mesh_ax = table.get(ax)
                        if mesh_ax is None:
                            continue
                        flagged_axes.add(ax)
                        findings.append(Finding(
                            rule=rules.SHARDING_CONTRACTION,
                            path=info.file.relpath, line=node.lineno,
                            symbol=info.qualname,
                            message=f"contraction dim '{letter}' carries "
                                    f"logical axis '{ax}', which "
                                    f"{tname} partitions over mesh axis "
                                    f"{mesh_ax!r} ({tpath}:"
                                    f"{tlines.get(ax, '?')}) — a split "
                                    f"reduction breaks the sharded-"
                                    f"decode bit-exactness contract"))
            # rule 2: row-parallel reduction with unanchored activation
            if weight_hits and unresolved_act:
                findings.append(Finding(
                    rule=rules.SHARDING_ANCHOR,
                    path=info.file.relpath, line=node.lineno,
                    symbol=info.qualname,
                    message=f"reduction against replicated row-parallel "
                            f"weight {weight_hits[0]!r} has an operand "
                            f"that does not flow from a constrain() "
                            f"anchor — without the pre-contraction "
                            f"anchor, propagation shards the contracted "
                            f"dim and XLA emits a partial-sum psum "
                            f"(bit-exactness contract)"))


# ------------------------------------------------------ rule 3 & 4

def _is_jit_call(graph: CallGraph, info: FunctionInfo,
                 call: ast.Call) -> bool:
    d = graph.resolved_dotted(call, info)
    return d is not None \
        and d.split(".")[-1] in rules.JIT_DOTTED_SUFFIXES


def _has_sharding_kw(call: ast.Call) -> bool:
    return any(kw.arg in rules.JIT_SHARDING_KWARGS
               for kw in call.keywords)


def _has_kw_splat(call: ast.Call) -> bool:
    return any(kw.arg is None for kw in call.keywords)


def _scope_withs(info: FunctionInfo) -> List[ast.AST]:
    """``with axis_rules(...)`` statements in this function."""
    out = []
    for node in _walk_no_nested(info.node):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                ce = item.context_expr
                if isinstance(ce, ast.Call):
                    d = dotted(ce.func)
                    if d is not None and d.split(".")[-1] in \
                            rules.SHARDING_SCOPE_CTXS:
                        out.append(node)
                        break
    return out


def _constrain_reachable(graph: CallGraph) -> Set[str]:
    """fqns that (transitively) call a constrain anchor."""
    direct: Set[str] = set()
    for tail in rules.SHARDING_CONSTRAIN_FUNCS:
        for _node, info in graph.calls_by_tail.get(tail, ()):
            direct.add(info.fqn)
    # reverse-BFS over the call graph
    callers: Dict[str, List[str]] = {}
    for fqn, rows in graph.edges().items():
        for callee, _line, _vs in rows:
            callers.setdefault(callee, []).append(fqn)
    seen = set(direct)
    queue = list(direct)
    while queue:
        fqn = queue.pop()
        for caller in callers.get(fqn, ()):
            if caller not in seen:
                seen.add(caller)
                queue.append(caller)
    return seen


def _opens_scope(graph: CallGraph, fqn: Optional[str],
                 depth: int = 0) -> bool:
    """The wrapped callable (or a callee, shallow) opens axis_rules
    itself — the train-step idiom (scope inside the traced body)."""
    if fqn is None or fqn not in graph.functions or depth > 2:
        return False
    info = graph.functions[fqn]
    if _scope_withs(info):
        return True
    return any(_opens_scope(graph, callee, depth + 1)
               for callee, _l, _vs in graph.edges().get(fqn, ()))


def _mesh_candidates(graph: CallGraph) -> Dict[str, FunctionInfo]:
    """Functions that can possibly hold a mesh-scope finding, from the
    shared side indexes — everything else is skipped whole."""
    cands: Dict[str, FunctionInfo] = {}
    tails = tuple(rules.JIT_DOTTED_SUFFIXES) + ("device_put",) \
        + tuple(rules.MESH_SCOPE_WRAPPERS)
    for tail in tails:
        for _node, info in graph.calls_by_tail.get(tail, ()):
            cands[info.fqn] = info
    for kw in rules.JIT_SHARDING_KWARGS:
        for _node, info in graph.calls_by_kwarg.get(kw, ()):
            cands[info.fqn] = info
    return cands


def _check_mesh_scopes(graph: CallGraph, findings: List[Finding],
                       emit_files) -> None:
    cands = _mesh_candidates(graph)
    constrainers = _constrain_reachable(graph) if cands else set()
    for fqn, info in sorted(cands.items()):
        if emit_files is not None \
                and info.file.relpath not in emit_files:
            continue
        scope_node_ids: Set[int] = set()
        for w in _scope_withs(info):
            for sub in ast.walk(w):
                scope_node_ids.add(id(sub))
        wrapper_args: Set[int] = set()
        for node in _walk_no_nested(info.node):
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if d is not None and d.split(".")[-1] in \
                        rules.MESH_SCOPE_WRAPPERS:
                    for a in node.args:
                        wrapper_args.add(id(a))

        for node in _walk_no_nested(info.node):
            if not isinstance(node, ast.Call):
                continue
            in_scope = id(node) in scope_node_ids \
                or id(node) in wrapper_args
            if _is_jit_call(graph, info, node) \
                    or _has_sharding_kw(node):
                pinned = _has_sharding_kw(node) or _has_kw_splat(node)
                if in_scope and not pinned:
                    findings.append(Finding(
                        rule=rules.SHARDING_UNPINNED,
                        path=info.file.relpath, line=node.lineno,
                        symbol=info.qualname,
                        message="jit inside a mesh scope without "
                                "in_shardings/out_shardings — unpinned "
                                "outputs let XLA re-place committed "
                                "sharded state"))
                if not in_scope and _has_sharding_kw(node) \
                        and node.args:
                    wrapped = None
                    arg = node.args[0]
                    if isinstance(arg, (ast.Name, ast.Attribute)):
                        fake = ast.Call(func=arg, args=[], keywords=[])
                        ast.copy_location(fake, arg)
                        wrapped, _vs = graph.resolve_call_cached(
                            fake, info)
                        if wrapped is None:
                            wrapped, _vs = graph.resolve_call(fake, info)
                    if wrapped is not None \
                            and wrapped in constrainers \
                            and not _opens_scope(graph, wrapped):
                        findings.append(Finding(
                            rule=rules.SHARDING_UNSCOPED,
                            path=info.file.relpath, line=node.lineno,
                            symbol=info.qualname,
                            message=f"sharded program "
                                    f"{wrapped.split(':')[-1]!r} (it "
                                    f"reaches constrain()) is jitted "
                                    f"with sharding kwargs outside any "
                                    f"axis_rules scope — constrain is a "
                                    f"silent no-op there, so the traced "
                                    f"program drops every anchor"))
                continue
            d = graph.resolved_dotted(node, info)
            if d is not None and d.split(".")[-1] == "device_put" \
                    and id(node) in scope_node_ids \
                    and len(node.args) < 2 and not node.keywords:
                findings.append(Finding(
                    rule=rules.SHARDING_UNPINNED,
                    path=info.file.relpath, line=node.lineno,
                    symbol=info.qualname,
                    message="device_put inside a mesh scope without a "
                            "sharding/placement argument — the value "
                            "lands on the default device, off-mesh"))


def check(graph: CallGraph, emit_files=None) -> List[Finding]:
    findings: List[Finding] = []
    graph.edges()  # ensure side indexes exist
    bitexact = load_rule_tables(graph.project)
    train_table = bitexact.get(rules.SHARDING_TRAIN_TABLE,
                               ({}, "", {}))[0]
    train_axes, decode_axes = load_param_axes(graph.project)
    row_par = row_parallel_weights(train_axes, decode_axes, train_table)
    _check_contractions(graph, findings, bitexact, decode_axes, row_par,
                        emit_files)
    _check_mesh_scopes(graph, findings, emit_files)
    return findings
