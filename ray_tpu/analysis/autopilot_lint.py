"""Autopilot family (#11): action handlers must fence AND audit.

**autopilot-unpaired-action** — in the closed-loop remediator
(``rules.AUTOPILOT_MODULES``), every action handler — a method whose
name starts with ``rules.AUTOPILOT_ACTION_PREFIX`` (``_act_``) — must
call BOTH ``self._fence_ok(...)`` and ``self._audit(...)`` somewhere
in its own body. This is the RPC_LEASE_PAIRS shape applied to control
actions instead of leases: the fence is what keeps a remediation from
fighting a cluster that already self-healed (stale epoch == the world
moved on), and the audit record is what makes an autonomous mutation
accountable after the fact. A handler missing either is exactly the
kind of "helpful" code path that double-kills a recovered gang or
leaves no trail for the post-mortem — flagged at ``make lint``, not
found in an incident review.

The pairing must be visible in the handler body itself, not satisfied
through a transitive callee: the point of the idiom is that a reader
of the handler sees the fence and the audit without chasing calls.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ray_tpu.analysis import rules
from ray_tpu.analysis.core import Finding, Project, qualname_of


def _self_calls(fn: ast.AST) -> set:
    """Names of every ``self.<name>(...)`` call in the function body."""
    out: set = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"):
            out.add(node.func.attr)
    return out


def check_project(project: Project,
                  emit_files: Optional[set] = None) -> List[Finding]:
    findings: List[Finding] = []
    for f in sorted(project.files, key=lambda s: s.relpath):
        if f.relpath not in rules.AUTOPILOT_MODULES:
            continue
        if emit_files is not None and f.relpath not in emit_files:
            continue
        stack: List[ast.AST] = []

        def visit(node: ast.AST) -> None:
            is_scope = isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef))
            if is_scope:
                stack.append(node)
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_scope:
                stack.pop()
            if not (isinstance(node, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
                    and node.name.startswith(
                        rules.AUTOPILOT_ACTION_PREFIX)
                    and stack and isinstance(stack[-1], ast.ClassDef)):
                return
            calls = _self_calls(node)
            missing = [c for c in (rules.AUTOPILOT_FENCE_CALL,
                                   rules.AUTOPILOT_AUDIT_CALL)
                       if c not in calls]
            if not missing:
                return
            findings.append(Finding(
                rule=rules.AUTOPILOT_UNPAIRED,
                path=f.relpath, line=node.lineno,
                symbol=qualname_of(stack + [node]),
                message=(f"action handler {node.name!r} never calls "
                         f"self.{' / self.'.join(missing)}: every "
                         f"autopilot action must pair an epoch-fence "
                         f"check ({rules.AUTOPILOT_FENCE_CALL}) with "
                         f"a durable audit record "
                         f"({rules.AUTOPILOT_AUDIT_CALL}) in its own "
                         f"body — an unfenced action can double-kill "
                         f"a gang the cluster already healed; an "
                         f"unaudited one is an unaccountable "
                         f"mutation")))

        visit(f.tree)
    return findings
