"""lifecycle-hygiene: swallowed exceptions and leak-on-error resources.

PR 3 spent days on slot/prefix-pin leaks whose root cause was error
paths that silently ate the exception or skipped the release. Two rules:

* swallowed-exception      — ``except Exception:``/``except
                             BaseException:``/bare ``except:`` whose
                             entire body is ``pass`` (or ``...``). Typed
                             narrow excepts (``except OSError: pass``)
                             are deliberate and exempt. Deliberate broad
                             silences get a pragma with a reason.
* missing-finally-release  — an acquire (``.acquire()``, ``selector
                             .register``, ``socket.socket()``/``open()``
                             not in ``with``) whose matching release
                             appears later in the SAME function but not
                             inside a ``finally`` block: any exception in
                             between leaks the resource. Functions that
                             never release (ownership handed elsewhere)
                             are not flagged.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.analysis import rules
from ray_tpu.analysis.callgraph import dotted, _walk_no_nested
from ray_tpu.analysis.core import Finding, Project, qualname_of

_BROAD = {"Exception", "BaseException"}


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, (ast.Name, ast.Attribute)):
        d = dotted(t)
        return d is not None and d.split(".")[-1] in _BROAD
    if isinstance(t, ast.Tuple):
        return any(
            (d := dotted(el)) is not None and d.split(".")[-1] in _BROAD
            for el in t.elts)
    return False


def _body_is_silent(body: List[ast.stmt]) -> bool:
    if len(body) != 1:
        return False
    stmt = body[0]
    if isinstance(stmt, ast.Pass):
        return True
    return (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis)


def _check_swallowed(tree: ast.AST, relpath: str,
                     findings: List[Finding]) -> None:
    stack: List[ast.AST] = []

    def visit(node: ast.AST) -> None:
        is_scope = isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef))
        if is_scope:
            stack.append(node)
        for child in ast.iter_child_nodes(node):
            visit(child)
        if isinstance(node, ast.ExceptHandler) \
                and _is_broad_handler(node) \
                and _body_is_silent(node.body):
            findings.append(Finding(
                rule=rules.SWALLOWED_EXCEPTION,
                path=relpath, line=node.lineno,
                symbol=qualname_of(stack),
                message="broad except with silent pass — log "
                        "(rate-limited) or narrow the exception type"))
        if is_scope:
            stack.pop()

    visit(tree)


def _in_finally_lines(fn_node: ast.AST) -> Set[int]:
    """Lines inside ``finally`` blocks OR ``except`` handlers: a release
    in either is exception-path remediation, not a leakable gap."""
    lines: Set[int] = set()

    def mark(stmts: List[ast.stmt]) -> None:
        for stmt in stmts:
            for sub in ast.walk(stmt):
                ln = getattr(sub, "lineno", None)
                if ln is not None:
                    lines.add(ln)

    for node in _walk_no_nested(fn_node):
        if isinstance(node, ast.Try):
            if node.finalbody:
                mark(node.finalbody)
            for h in node.handlers:
                mark(h.body)
    return lines


def _with_context_lines(fn_node: ast.AST) -> Set[int]:
    """Line numbers of expressions used as ``with`` context managers —
    those handle their own release."""
    lines: Set[int] = set()
    for node in _walk_no_nested(fn_node):
        if isinstance(node, ast.With):
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    ln = getattr(sub, "lineno", None)
                    if ln is not None:
                        lines.add(ln)
    return lines


def _recv_name(call: ast.Call) -> Optional[str]:
    """Receiver of a method call as a dotted key (``x.acquire()`` -> x,
    ``self._selector.register(...)`` -> ``self._selector``)."""
    if isinstance(call.func, ast.Attribute):
        return dotted(call.func.value)
    return None


def _check_releases(fn_node: ast.AST, relpath: str, symbol: str,
                    findings: List[Finding]) -> None:
    finally_lines = _in_finally_lines(fn_node)
    with_lines = _with_context_lines(fn_node)

    method_pairs = dict(rules.ACQUIRE_RELEASE_METHODS)
    release_names = set(method_pairs.values()) | {
        rel for _, rel in rules.ACQUIRE_RELEASE_DOTTED}

    # receiver -> [(line, acquire-verb)] and receiver -> [(line, bool
    # in_finally)] for releases. "Receiver" keys the pairing: x.acquire /
    # x.release, sock = socket.socket() / sock.close().
    acquires: Dict[Tuple[str, str], List[int]] = {}
    releases: Dict[Tuple[str, str], List[Tuple[int, bool]]] = {}

    for node in _walk_no_nested(fn_node):
        # assignment-style acquires: x = socket.socket(...) / open(...)
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call):
            d = dotted(node.value.func)
            for acq_dotted, release in rules.ACQUIRE_RELEASE_DOTTED:
                if d == acq_dotted and node.lineno not in with_lines:
                    acquires.setdefault(
                        (node.targets[0].id, release), []).append(
                        node.lineno)
        if not isinstance(node, ast.Call):
            continue
        recv = _recv_name(node)
        meth = node.func.attr if isinstance(node.func, ast.Attribute) \
            else None
        if recv is None or meth is None:
            continue
        if meth in method_pairs and node.lineno not in with_lines:
            acquires.setdefault((recv, method_pairs[meth]), []).append(
                node.lineno)
        if meth in release_names:
            releases.setdefault((recv, meth), []).append(
                (node.lineno, node.lineno in finally_lines))

    for (recv, release), acq_lines in acquires.items():
        rel_sites = releases.get((recv, release))
        if not rel_sites:
            continue  # no release here: ownership transferred
        acq_line = min(acq_lines)
        later = [(ln, fin) for ln, fin in rel_sites if ln > acq_line]
        if not later:
            continue
        if any(fin for _, fin in later):
            continue  # protected by a finally
        rel_line = min(ln for ln, _ in later)
        if rel_line - acq_line <= 1:
            continue  # nothing in between can raise
        findings.append(Finding(
            rule=rules.MISSING_FINALLY,
            path=relpath, line=acq_line, symbol=symbol,
            message=f"`{recv}` acquired here but released at line "
                    f"{rel_line} outside any finally — an exception in "
                    f"between leaks it"))


def check_project(project: Project, emit_files=None) -> List[Finding]:
    findings: List[Finding] = []
    for f in project.files:
        if emit_files is not None and f.relpath not in emit_files:
            continue  # purely per-file rules: skip entirely
        _check_swallowed(f.tree, f.relpath, findings)
        stack: List[ast.AST] = []

        def visit(node: ast.AST) -> None:
            is_scope = isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef))
            if is_scope:
                stack.append(node)
                if not isinstance(node, ast.ClassDef):
                    _check_releases(node, f.relpath, qualname_of(stack),
                                    findings)
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_scope:
                stack.pop()

        visit(f.tree)
    return findings
