"""deadline-safety: nothing in the control plane may block forever.

The runtime's signature failure mode is the silent distributed hang —
one member parks on an unbounded wait and the whole gang idles. Five
rules on the shared call graph police *time* the way the lock/lease
families police state:

* ``unbounded-blocking-call`` — reactor-blocking-call generalized past
  the reactor: every thread entry point graftlint already enumerates
  (RPC handlers, ``threading.Thread``/``Timer`` targets, executor
  submissions — the reactor itself stays family #1's job) is BFS-walked
  and any reachable ``Event.wait()`` / ``Queue.get()`` / ``join()`` /
  ``future.result()`` / socket ``recv`` without a finite bound is
  flagged. Bounded = the timeout-position argument is present and not
  the literal ``None``; queue receivers are ctor-typed so dict/
  contextvar ``.get`` never matches.
* ``rpc-call-no-timeout`` — in the control-plane modules
  (rules.DEADLINE_RPC_SCOPE_PREFIXES), every literal ``.call("x",...)``
  and typed-stub call must carry ``timeout=``: the client transport
  treats ``timeout=None`` as park-forever, and a faultinject ``drop``
  rule on the endpoint (or a dead peer mid-call) wedges the caller.
* ``deadline-not-propagated`` — a function accepting a ``timeout_s`` /
  ``deadline`` budget that hands the FULL budget to 2+ blocking/RPC
  sites (N× the caller's budget) or makes an unbounded one, without a
  remaining-time idiom (``util.deadline.Deadline`` or raw
  ``time.monotonic`` arithmetic). One budget-consuming call is a
  pass-through, not a violation.
* ``retry-unbounded`` — ``while True`` / ``itertools.count`` loops
  re-issuing dial/RPC verbs with no backoff sleep, attempt counter, or
  deadline check in the body (the PR 12 reconnect-storm shape).
* ``timeout-knob-dead`` — every ``*_timeout_s`` knob in core/config.py
  must be READ somewhere in the package (``config.<knob>``); a knob
  never threaded to a wait site is dead documentation, mirroring
  rpc-dead-endpoint.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.analysis import rules
from ray_tpu.analysis.callgraph import (CallGraph, FunctionInfo, _short,
                                        _walk_no_nested, dotted)
from ray_tpu.analysis.core import Finding

# ----------------------------------------------------- receiver typing


def _ctor_typed(graph: CallGraph, ctors: Set[str],
                ) -> Tuple[Set[Tuple[str, Optional[str], str]],
                           Set[Tuple[str, str]]]:
    """Receivers typed by construction: ``self.x = Ctor()`` anywhere in
    a class -> (module, cls, attr); ``q = Ctor()`` -> (fqn, local)."""
    self_attrs: Set[Tuple[str, Optional[str], str]] = set()
    fn_locals: Set[Tuple[str, str]] = set()
    for fqn, info in graph.functions.items():
        for node in _walk_no_nested(info.node):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.value, ast.Call)):
                continue
            rd = graph.resolved_dotted(node.value, info)
            if rd is None or rd not in ctors:
                continue
            tgt = node.targets[0]
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                self_attrs.add((info.module, info.cls, tgt.attr))
            elif isinstance(tgt, ast.Name):
                fn_locals.add((fqn, tgt.id))
    return self_attrs, fn_locals


def _stub_typed(graph: CallGraph
                ) -> Tuple[Set[Tuple[str, Optional[str], str]],
                           Set[Tuple[str, str]]]:
    """Receivers typed as generated RPC stubs (``ControllerStub(...)``
    and friends, rules.RPC_STUBS_MODULE)."""
    self_attrs: Set[Tuple[str, Optional[str], str]] = set()
    fn_locals: Set[Tuple[str, str]] = set()
    prefix = rules.RPC_STUBS_MODULE + "."
    for fqn, info in graph.functions.items():
        for node in _walk_no_nested(info.node):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.value, ast.Call)):
                continue
            if not _is_stub_ctor(graph, node.value, info, prefix):
                continue
            tgt = node.targets[0]
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                self_attrs.add((info.module, info.cls, tgt.attr))
            elif isinstance(tgt, ast.Name):
                fn_locals.add((fqn, tgt.id))
    return self_attrs, fn_locals


def _is_stub_ctor(graph: CallGraph, call: ast.Call, info: FunctionInfo,
                  prefix: str) -> bool:
    rd = graph.resolved_dotted(call, info)
    if rd is not None and rd.startswith(prefix):
        return True
    # unresolved import paths: fall back on the ``*Stub(...)`` spelling
    d = dotted(call.func)
    return d is not None and d.split(".")[-1].endswith("Stub")


# ------------------------------------------------- wait-site inventory


def _timeout_arg(node: ast.Call, kwname: str,
                 pos: int) -> Optional[ast.AST]:
    for kw in node.keywords:
        if kw.arg == kwname:
            return kw.value
    if len(node.args) > pos:
        return node.args[pos]
    return None


def _is_none(expr: Optional[ast.AST]) -> bool:
    return isinstance(expr, ast.Constant) and expr.value is None


def _is_false(expr: Optional[ast.AST]) -> bool:
    return isinstance(expr, ast.Constant) and expr.value is False


def wait_sites(graph: CallGraph
               ) -> Dict[str, List[Tuple[int, str, bool]]]:
    """fqn -> [(line, label, bounded)] for every wait-verb call.
    ``get`` only on queue-typed receivers; socket recv verbs bounded
    when the enclosing module manages socket modes."""
    graph.edges()  # calls_by_tail is built as an edge-walk side index
    q_attrs, q_locals = _ctor_typed(
        graph, set(rules.DEADLINE_QUEUE_CTORS))
    out: Dict[str, List[Tuple[int, str, bool]]] = {}

    def add(info: FunctionInfo, line: int, label: str,
            bounded: bool) -> None:
        out.setdefault(info.fqn, []).append((line, label, bounded))

    for verb, (kwname, pos, label) in rules.DEADLINE_WAIT_VERBS.items():
        for node, info in graph.calls_by_tail.get(verb, ()):
            if not isinstance(node.func, ast.Attribute):
                continue
            recv = node.func.value
            if isinstance(recv, ast.Constant):
                continue  # "\n".join(...) and friends
            if verb == "get":
                typed = False
                if (isinstance(recv, ast.Attribute)
                        and isinstance(recv.value, ast.Name)
                        and recv.value.id == "self"):
                    typed = (info.module, info.cls,
                             recv.attr) in q_attrs
                elif isinstance(recv, ast.Name):
                    typed = (info.fqn, recv.id) in q_locals
                if not typed:
                    continue
                block = _timeout_arg(node, rules.DEADLINE_NONBLOCK_KWARG,
                                     0)
                if _is_false(block):
                    continue  # non-blocking get
            t = _timeout_arg(node, kwname, pos)
            add(info, node.lineno, label,
                t is not None and not _is_none(t))

    # socket reads: bounded only via settimeout/setblocking, checked at
    # module granularity (the reactor's nonblocking fds, _connect's
    # bounded dial)
    managed: Set[str] = set()
    for mode_call in rules.DEADLINE_SOCKET_MODE_CALLS:
        for node, info in graph.calls_by_tail.get(mode_call, ()):
            managed.add(info.module)
    for verb in rules.DEADLINE_SOCKET_VERBS:
        for node, info in graph.calls_by_tail.get(verb, ()):
            if not isinstance(node.func, ast.Attribute):
                continue
            add(info, node.lineno, f"socket {verb} with unmanaged "
                "timeout", info.module in managed)
    return out


# -------------------------------------------------- rpc-site inventory


def _stub_param(info: FunctionInfo, name: str) -> bool:
    a = info.node.args
    params = {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)}
    return name in params and (
        name in rules.DEADLINE_STUB_PARAM_NAMES
        or name.endswith(rules.DEADLINE_STUB_PARAM_SUFFIX))


def rpc_sites(graph: CallGraph
              ) -> Dict[str, List[Tuple[int, str, bool]]]:
    """fqn -> [(line, "method", bounded)] for literal ``.call`` and
    typed-stub RPC sites (``notify`` is fire-and-forget: exempt)."""
    s_attrs, s_locals = _stub_typed(graph)
    prefix = rules.RPC_STUBS_MODULE + "."
    out: Dict[str, List[Tuple[int, str, bool]]] = {}

    for fqn, info in graph.functions.items():
        if info.file.relpath == rules.RPC_STUBS_PATH:
            continue  # generated pass-throughs thread their own kwarg
        for node in _walk_no_nested(info.node):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            meth = None
            if (node.func.attr == "call" and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                meth = node.args[0].value
            else:
                recv = node.func.value
                stubbed = False
                if (isinstance(recv, ast.Attribute)
                        and isinstance(recv.value, ast.Name)
                        and recv.value.id == "self"):
                    stubbed = (info.module, info.cls,
                               recv.attr) in s_attrs
                elif isinstance(recv, ast.Name):
                    stubbed = (info.fqn, recv.id) in s_locals \
                        or _stub_param(info, recv.id)
                elif isinstance(recv, ast.Call):
                    stubbed = _is_stub_ctor(graph, recv, info, prefix)
                if stubbed:
                    meth = node.func.attr
            if meth is None:
                continue
            t = _timeout_arg(node, "timeout", 10**9)  # kwarg-only
            out.setdefault(fqn, []).append(
                (node.lineno, meth,
                 t is not None and not _is_none(t)))
    return out


# ------------------------------------------------------------- checks


def _thread_roots(graph: CallGraph) -> Dict[str, str]:
    """root fqn -> entry key, for every NON-reactor, NON-synthetic
    thread entry (the reactor stays reactor-blocking-call's beat;
    ``caller`` would make the whole package 'thread code')."""
    from ray_tpu.analysis.guarded_by import thread_entries

    entries, _self_conc = thread_entries(graph)
    roots: Dict[str, str] = {}
    for key, fqns in entries.items():
        if key in ("caller", "reactor"):
            continue
        for fqn in fqns:
            roots.setdefault(fqn, key)
    return roots


def _check_unbounded(graph: CallGraph, waits, emit_files
                     ) -> List[Finding]:
    roots = _thread_roots(graph)
    findings: List[Finding] = []
    paths: Dict[str, Tuple[str, List[str]]] = {
        fqn: (key, [_short(fqn)]) for fqn, key in roots.items()}
    queue = list(paths)
    while queue:
        fqn = queue.pop(0)
        key, chain = paths[fqn]
        info = graph.functions[fqn]
        emit = emit_files is None or info.file.relpath in emit_files
        if emit:
            for line, label, bounded in waits.get(fqn, ()):
                if bounded:
                    continue
                via = " -> ".join(chain)
                findings.append(Finding(
                    rule=rules.DEADLINE_UNBOUNDED,
                    path=info.file.relpath, line=line,
                    symbol=info.qualname,
                    message=f"{label} on thread entry '{key}' "
                            f"(reachable via {via}); pass a finite "
                            f"timeout or thread a Deadline"))
        for callee, _line, _vs in graph.edges().get(fqn, ()):
            if callee not in paths:
                paths[callee] = (key, chain + [_short(callee)])
                queue.append(callee)
    return findings


def _check_rpc_timeout(graph: CallGraph, all_rpc, emit_files
                       ) -> List[Finding]:
    findings: List[Finding] = []
    for fqn, sites in all_rpc.items():
        info = graph.functions[fqn]
        if not info.file.relpath.startswith(
                rules.DEADLINE_RPC_SCOPE_PREFIXES):
            continue
        if emit_files is not None \
                and info.file.relpath not in emit_files:
            continue
        for line, meth, bounded in sites:
            if bounded:
                continue
            findings.append(Finding(
                rule=rules.DEADLINE_RPC_NO_TIMEOUT,
                path=info.file.relpath, line=line,
                symbol=info.qualname,
                message=f"control-plane RPC '{meth}' without timeout= "
                        f"(timeout=None parks forever if the reply "
                        f"never lands)"))
    return findings


def _mentions(node: ast.AST, name: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == name
               for n in ast.walk(node))


def _budget_passes(fn_node: ast.AST, budget: str) -> int:
    """How many distinct downstream flows consume the budget: the
    OUTERMOST calls mentioning it (nested calls are one flow, so
    ``outs.append(w.run(cmd, timeout))`` counts once), with all
    ``return``-position flows collapsed to one (alternative exits
    cannot compound) and ``raise`` constructors skipped (an error
    message quoting the budget consumes nothing)."""
    count = 0
    return_hit = False

    def rec(node, in_call: bool, in_return: bool) -> None:
        nonlocal count, return_hit
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(child, ast.Raise):
                continue
            child_in_call = in_call
            if isinstance(child, ast.Call) and not in_call \
                    and _mentions(child, budget):
                if in_return:
                    return_hit = True
                else:
                    count += 1
                child_in_call = True
            rec(child, child_in_call,
                in_return or isinstance(child, ast.Return))

    rec(fn_node, False, False)
    return count + (1 if return_hit else 0)


def _check_propagation(graph: CallGraph, waits, all_rpc, emit_files
                       ) -> List[Finding]:
    findings: List[Finding] = []
    for fqn, info in graph.functions.items():
        if emit_files is not None \
                and info.file.relpath not in emit_files:
            continue
        a = info.node.args
        params = [p.arg for p in
                  (a.posonlyargs + a.args + a.kwonlyargs)]
        budget = next((p for p in params
                       if p in rules.DEADLINE_PARAM_NAMES), None)
        if budget is None:
            continue
        sites = list(waits.get(fqn, ())) + list(all_rpc.get(fqn, ()))
        if not sites:
            continue
        # remaining-time idiom anywhere in the body: Deadline attrs
        # (.remaining/.expired/.sub) or raw monotonic arithmetic
        idiom = False
        for node in _walk_no_nested(info.node):
            if isinstance(node, ast.Attribute) \
                    and node.attr in rules.DEADLINE_IDIOM_ATTRS:
                idiom = True
                break
            if isinstance(node, ast.Call):
                rd = graph.resolved_dotted(node, info)
                if rd in rules.DEADLINE_IDIOM_DOTTED or (
                        rd is not None and rd.startswith(
                            rules.DEADLINE_HELPER_MODULE)):
                    idiom = True
                    break
        if idiom:
            continue
        unbounded = [s for s in sites if not s[2]]
        # distinct downstream flows the budget is handed to
        passes = _budget_passes(info.node, budget)
        if unbounded:
            line, label, _ = unbounded[0]
            msg = (f"accepts '{budget}' but makes an unbounded "
                   f"call ({label}) — the budget is dropped")
        elif passes >= 2:
            line = sites[0][0]
            msg = (f"hands the full '{budget}' budget to {passes} "
                   f"downstream calls (N x the caller's budget); "
                   f"thread Deadline.remaining()")
        else:
            continue
        findings.append(Finding(
            rule=rules.DEADLINE_NOT_PROPAGATED,
            path=info.file.relpath, line=line, symbol=info.qualname,
            message=msg))
    return findings


def _loop_is_infinite(graph: CallGraph, node: ast.AST,
                      info: FunctionInfo) -> bool:
    if isinstance(node, ast.While):
        return isinstance(node.test, ast.Constant) \
            and bool(node.test.value)
    if isinstance(node, ast.For) and isinstance(node.iter, ast.Call):
        rd = graph.resolved_dotted(node.iter, info)
        return rd == "itertools.count"
    return False


def _check_retry(graph: CallGraph, emit_files) -> List[Finding]:
    findings: List[Finding] = []
    retry_verbs = set(rules.DEADLINE_RETRY_VERBS)
    backoff = set(rules.DEADLINE_BACKOFF_CALLS)
    for fqn, info in graph.functions.items():
        if emit_files is not None \
                and info.file.relpath not in emit_files:
            continue
        for node in _walk_no_nested(info.node):
            if not _loop_is_infinite(graph, node, info):
                continue
            has_rpc = False
            bounded = False
            for sub in ast.walk(node):
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(sub, ast.Call):
                    tail = sub.func.attr \
                        if isinstance(sub.func, ast.Attribute) else (
                            sub.func.id
                            if isinstance(sub.func, ast.Name) else None)
                    if tail in retry_verbs:
                        has_rpc = True
                    if tail in backoff:
                        bounded = True
                    rd = graph.resolved_dotted(sub, info)
                    if rd in rules.DEADLINE_IDIOM_DOTTED:
                        bounded = True
                elif isinstance(sub, ast.Attribute) \
                        and sub.attr in rules.DEADLINE_IDIOM_ATTRS:
                    bounded = True
                elif isinstance(sub, ast.AugAssign):
                    bounded = True  # attempt counter
            if has_rpc and not bounded:
                findings.append(Finding(
                    rule=rules.DEADLINE_RETRY_UNBOUNDED,
                    path=info.file.relpath, line=node.lineno,
                    symbol=info.qualname,
                    message="infinite loop re-issuing dial/RPC calls "
                            "with no backoff, attempt bound, or "
                            "deadline check (reconnect-storm shape)"))
    return findings


def _check_dead_knobs(graph: CallGraph, emit_files) -> List[Finding]:
    cfg = next((f for f in graph.project.files
                if f.relpath == rules.DEADLINE_CONFIG_MODULE_PATH),
               None)
    if cfg is None:
        return []
    if emit_files is not None and cfg.relpath not in emit_files:
        return []
    knobs: List[Tuple[str, int]] = []
    for node in ast.walk(cfg.tree):
        # the registry is declared annotated (_FLAG_DEFS: Dict[...] =
        # {...}), so match both assignment spellings
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            tgt = node.target
        else:
            continue
        if not (isinstance(tgt, ast.Name)
                and tgt.id == rules.DEADLINE_CONFIG_FLAGS_NAME
                and isinstance(node.value, ast.Dict)):
            continue
        for key in node.value.keys:
            if isinstance(key, ast.Constant) \
                    and isinstance(key.value, str) \
                    and key.value.endswith(rules.DEADLINE_KNOB_SUFFIX):
                knobs.append((key.value, key.lineno))
    findings: List[Finding] = []
    for name, line in knobs:
        probe = f".{name}"
        if any(probe in f.text for f in graph.project.files
               if f.relpath != cfg.relpath):
            continue
        findings.append(Finding(
            rule=rules.DEADLINE_KNOB_DEAD,
            path=cfg.relpath, line=line, symbol=name,
            message=f"timeout knob '{name}' is registered but never "
                    f"read (config.{name} appears nowhere): it bounds "
                    f"no wait site"))
    return findings


def check(graph: CallGraph, emit_files=None) -> List[Finding]:
    waits = wait_sites(graph)
    all_rpc = rpc_sites(graph)
    findings = _check_unbounded(graph, waits, emit_files)
    findings += _check_rpc_timeout(graph, all_rpc, emit_files)
    findings += _check_propagation(graph, waits, all_rpc, emit_files)
    findings += _check_retry(graph, emit_files)
    findings += _check_dead_knobs(graph, emit_files)
    return findings
