"""Fence-safety family (#12): the epoch-fence protocol, statically.

Every fault-tolerance layer since PR 12 rides one idiom: writes to
shared control state carry a monotonic epoch, the owner of the state
rejects strictly-older (or, for version clocks, not-strictly-newer)
writes, and a REJECTED writer must treat the verdict as "you were
deposed" — self-fence, stop reconciling, or raise. The idiom is spread
across eleven files and is pure convention; these rules pin it:

**fence-result-ignored** — a fenced write (``rules.FENCED_WRITE_APIS``
by call tail, ``rules.FENCED_WRITE_EPOCH_ARG`` for publish-shaped APIs
that are fenced only when an epoch rides the call, plus the
``client.call("kv_put_fenced", ...)`` string form) whose result is
discarded: a bare expression statement, an assignment to a name that
is never read, or a result propagated through a bare ``return`` whose
own callers discard it (the lifetime.py via-self idiom — a function
that just forwards the verdict is a *fence carrier*, and the
discarding is charged to ITS call sites, transitively). A zombie that
ignores the stale-epoch verdict keeps acting as the owner: the exact
split-brain the fencing exists to prevent.

**unfenced-mutation-in-fenced-class** — inside a class listed in
``rules.FENCED_STATE_CLASSES``, a raw (unfenced) controller-KV write
spelling, or a publish-shaped call WITHOUT its epoch argument. The
class's state is fenced or it isn't: one bypassing write re-opens the
hole for every fenced one.

**epoch-compare-direction** — at the comparison sites named in
``rules.EPOCH_COMPARE_TABLE``, the guard's direction must match the
clock's semantics: "equal-ok" clocks (epoch fences) reject only
STRICTLY older writes — ``incoming <= stored`` drops a legitimate
same-epoch republish; "strict" clocks (weight versions) must reject
equal — ``incoming < stored`` lets a replayed version re-apply.

**epoch-not-threaded** — a fenced publish in a fenced class whose
dict-literal payload lacks the clock key (``rules.
FENCED_PAYLOAD_RULES``): subscribers run their OWN staleness check
against the payload's epoch/version (the router-snapshot idiom), so a
payload without it makes every downstream fence blind.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.analysis import rules
from ray_tpu.analysis.callgraph import (CallGraph, FunctionInfo, dotted,
                                        _walk_no_nested)
from ray_tpu.analysis.core import Finding

_MIRROR = {ast.Lt: ast.Gt, ast.LtE: ast.GtE,
           ast.Gt: ast.Lt, ast.GtE: ast.LtE}
# ops flagged with the STORED clock normalized to the right-hand side
_BAD_OPS = {"equal-ok": (ast.LtE, ast.Gt), "strict": (ast.Lt, ast.GtE)}


def _call_tail(call: ast.Call) -> Optional[str]:
    func = call.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _is_none(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value is None


def _has_epoch_arg(call: ast.Call, kwarg: str, pos: int,
                   offset: int = 0) -> bool:
    """True when an epoch rides the call: the named kwarg (non-None
    literal), or a positional at ``pos`` (+offset for the string-RPC
    form, whose args shift right past the method name)."""
    for kw in call.keywords:
        if kw.arg == kwarg and not _is_none(kw.value):
            return True
    i = pos + offset
    return len(call.args) > i and not _is_none(call.args[i])


def _fenced_call_sites(graph: CallGraph
                       ) -> List[Tuple[ast.Call, FunctionInfo, str]]:
    """Every (call node, enclosing function, api name) writing through
    a fenced API — stub/handler tails, epoch-carrying publishes, and
    the client.call("<name>", ...) string form."""
    graph.edges()
    sites: List[Tuple[ast.Call, FunctionInfo, str]] = []
    for name in rules.FENCED_WRITE_APIS:
        for call, info in graph.calls_by_tail.get(name, ()):
            sites.append((call, info, name))
    for name, (kwarg, pos) in rules.FENCED_WRITE_EPOCH_ARG.items():
        for call, info in graph.calls_by_tail.get(name, ()):
            if _has_epoch_arg(call, kwarg, pos):
                sites.append((call, info, name))
    for verb in rules.FENCED_RPC_VERBS:
        for call, info in graph.calls_by_tail.get(verb, ()):
            if not (call.args and isinstance(call.args[0], ast.Constant)
                    and isinstance(call.args[0].value, str)):
                continue
            name = call.args[0].value
            if name in rules.FENCED_WRITE_APIS:
                sites.append((call, info, name))
            elif name in rules.FENCED_WRITE_EPOCH_ARG:
                kwarg, pos = rules.FENCED_WRITE_EPOCH_ARG[name]
                if _has_epoch_arg(call, kwarg, pos, offset=1):
                    sites.append((call, info, name))
    return sites


def _parents(fn_node: ast.AST) -> Dict[int, ast.AST]:
    out: Dict[int, ast.AST] = {}
    for node in ast.walk(fn_node):
        for child in ast.iter_child_nodes(node):
            out[id(child)] = node
    return out


def _name_loads(fn_node: ast.AST, name: str) -> List[ast.Name]:
    return [n for n in _walk_no_nested(fn_node)
            if isinstance(n, ast.Name) and n.id == name
            and isinstance(n.ctx, ast.Load)]


def _classify(call: ast.Call, info: FunctionInfo) -> str:
    """How the fenced result is used: 'discarded' (never looked at),
    'carrier' (forwarded verbatim via return — charge the callers), or
    'consumed' (anything else: tests, call args, attribute stores)."""
    parents = _parents(info.node)
    node: ast.AST = call
    parent = parents.get(id(node))
    while isinstance(parent, ast.Await):
        node, parent = parent, parents.get(id(parent))
    if isinstance(parent, ast.Expr):
        return "discarded"
    if isinstance(parent, ast.Return):
        return "carrier"
    if isinstance(parent, ast.Assign) and parent.value is node \
            and len(parent.targets) == 1 \
            and isinstance(parent.targets[0], ast.Name):
        loads = _name_loads(info.node, parent.targets[0].id)
        if not loads:
            return "discarded"
        returned = set()
        for n in _walk_no_nested(info.node):
            if isinstance(n, ast.Return) and isinstance(n.value, ast.Name):
                returned.add(id(n.value))
        if all(id(n) in returned for n in loads):
            return "carrier"
        return "consumed"
    return "consumed"


def _check_result_ignored(graph: CallGraph,
                          findings: List[Finding]) -> None:
    sites = _fenced_call_sites(graph)
    # (api name, chain of carrier hops) per pending site; carriers fan
    # the classification out to their own call sites, transitively.
    work = [(call, info, api, []) for call, info, api in sites]
    seen_carriers: Set[Tuple[str, str]] = set()
    while work:
        call, info, api, chain = work.pop()
        verdict = _classify(call, info)
        if verdict == "consumed":
            continue
        if verdict == "carrier":
            if (info.fqn, api) in seen_carriers:
                continue
            seen_carriers.add((info.fqn, api))
            tail = info.node.name
            for caller_call, caller_info in \
                    graph.calls_by_tail.get(tail, ()):
                callee, _ = graph.resolve_call_cached(caller_call,
                                                      caller_info)
                if callee == info.fqn:
                    work.append((caller_call, caller_info, api,
                                 chain + [info.qualname]))
            continue
        via = f" (via the {' -> '.join(chain)} fence carrier)" \
            if chain else ""
        findings.append(Finding(
            rule=rules.FENCE_RESULT_IGNORED,
            path=info.file.relpath, line=call.lineno,
            symbol=info.qualname,
            message=(f"result of fenced write {api!r} is discarded"
                     f"{via}: {rules.FENCED_WRITE_APIS.get(api) or 'a stale epoch returns a rejection'}"
                     f" — a writer that ignores the verdict keeps "
                     f"acting as the owner after being deposed "
                     f"(self-fence or raise on a stale write)")))


def _check_unfenced_mutation(graph: CallGraph,
                             findings: List[Finding]) -> None:
    banned_tails = {t for spellings in rules.FENCED_STATE_CLASSES.values()
                    for t in spellings}
    for tail in sorted(banned_tails):
        for call, info in graph.calls_by_tail.get(tail, ()):
            banned = rules.FENCED_STATE_CLASSES.get(info.cls or "", ())
            if tail in banned:
                findings.append(Finding(
                    rule=rules.FENCE_UNFENCED_MUTATION,
                    path=info.file.relpath, line=call.lineno,
                    symbol=info.qualname,
                    message=(f"raw {tail!r} write inside fenced class "
                             f"{info.cls}: this class's control state "
                             f"is epoch-fenced — an unfenced write "
                             f"lets a deposed instance clobber the "
                             f"new owner's state (use the fenced API "
                             f"with the instance epoch)")))
    for verb in rules.FENCED_RPC_VERBS:
        for call, info in graph.calls_by_tail.get(verb, ()):
            if not (call.args and isinstance(call.args[0], ast.Constant)
                    and isinstance(call.args[0].value, str)):
                continue
            name = call.args[0].value
            banned = rules.FENCED_STATE_CLASSES.get(info.cls or "", ())
            if name in banned:
                findings.append(Finding(
                    rule=rules.FENCE_UNFENCED_MUTATION,
                    path=info.file.relpath, line=call.lineno,
                    symbol=info.qualname,
                    message=(f"raw call({name!r}, ...) inside fenced "
                             f"class {info.cls}: use the fenced API "
                             f"with the instance epoch")))
    for name, (kwarg, pos) in rules.FENCED_WRITE_EPOCH_ARG.items():
        for call, info in graph.calls_by_tail.get(name, ()):
            if info.cls in rules.FENCED_STATE_CLASSES \
                    and not _has_epoch_arg(call, kwarg, pos):
                findings.append(Finding(
                    rule=rules.FENCE_UNFENCED_MUTATION,
                    path=info.file.relpath, line=call.lineno,
                    symbol=info.qualname,
                    message=(f"{name!r} without its {kwarg!r} argument "
                             f"inside fenced class {info.cls}: the hub "
                             f"treats an epoch-less publish as "
                             f"unfenced, so a deposed publisher "
                             f"overwrites the new owner's snapshot")))


def _dotted_of(node: ast.AST) -> Optional[str]:
    while isinstance(node, ast.Subscript):
        node = node.value
    return dotted(node)


def _matches(node: ast.AST, suffix: str) -> bool:
    d = _dotted_of(node)
    return d is not None and (d == suffix or d.endswith("." + suffix))


def _check_compare_direction(graph: CallGraph,
                             findings: List[Finding]) -> None:
    by_path: Dict[str, List[Tuple[str, str]]] = {}
    for path, suffix, mode in rules.EPOCH_COMPARE_TABLE:
        by_path.setdefault(path, []).append((suffix, mode))
    by_rel = {f.relpath: f for f in graph.project.files}
    for path, entries in by_path.items():
        src = by_rel.get(path)
        if src is None:
            continue
        stack: List[ast.AST] = []

        def visit(node: ast.AST) -> None:
            is_scope = isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef))
            if is_scope:
                stack.append(node)
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_scope:
                stack.pop()
            if not (isinstance(node, ast.Compare)
                    and len(node.ops) == 1):
                return
            left, op, right = node.left, node.ops[0], node.comparators[0]
            if type(op) not in _MIRROR:
                return
            for suffix, mode in entries:
                lm, rm = _matches(left, suffix), _matches(right, suffix)
                if lm == rm:        # neither side, or ambiguous
                    continue
                incoming = left if rm else right
                norm_op = type(op) if rm else _MIRROR[type(op)]
                if isinstance(incoming, ast.Constant):
                    continue        # sentinel checks, not protocol
                if norm_op in _BAD_OPS[mode]:
                    want = ("strictly-older-loses (equal must be "
                            "ACCEPTED: a same-epoch republish is "
                            "legitimate)") if mode == "equal-ok" else \
                        ("strictly-newer-wins (equal must be "
                         "REJECTED: an equal version is a replay)")
                    from ray_tpu.analysis.core import qualname_of
                    findings.append(Finding(
                        rule=rules.FENCE_COMPARE_DIRECTION,
                        path=path, line=node.lineno,
                        symbol=qualname_of(stack),
                        message=(f"comparison against stored clock "
                                 f"{suffix!r} has the wrong direction "
                                 f"for a {mode!r} fence: the protocol "
                                 f"is {want}")))

        visit(src.tree)


def _dict_payload(call: ast.Call, argidx: int,
                  info: FunctionInfo) -> Optional[ast.Dict]:
    """The payload argument as a dict literal — direct, or resolved
    through the last same-function assignment to a local name before
    the call. Opaque payload expressions return None (not evidence)."""
    if len(call.args) <= argidx:
        return None
    payload = call.args[argidx]
    if isinstance(payload, ast.Dict):
        return payload
    if not isinstance(payload, ast.Name):
        return None
    best: Optional[ast.Dict] = None
    best_line = -1
    for node in _walk_no_nested(info.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == payload.id \
                and isinstance(node.value, ast.Dict) \
                and best_line < node.lineno <= call.lineno:
            best, best_line = node.value, node.lineno
    return best


def _check_epoch_threaded(graph: CallGraph,
                          findings: List[Finding]) -> None:
    for (cls, tail), (argidx, key) in \
            sorted(rules.FENCED_PAYLOAD_RULES.items()):
        for call, info in graph.calls_by_tail.get(tail, ()):
            if info.cls != cls:
                continue
            payload = _dict_payload(call, argidx, info)
            if payload is None:
                continue
            keys = {k.value for k in payload.keys
                    if isinstance(k, ast.Constant)}
            if key not in keys:
                findings.append(Finding(
                    rule=rules.FENCE_EPOCH_NOT_THREADED,
                    path=info.file.relpath, line=call.lineno,
                    symbol=info.qualname,
                    message=(f"payload of fenced {tail!r} in {cls} "
                             f"lacks the {key!r} key: subscribers run "
                             f"their own staleness check against the "
                             f"payload clock — without it every "
                             f"downstream fence is blind")))


def check(graph: CallGraph,
          emit_files: Optional[set] = None) -> List[Finding]:
    findings: List[Finding] = []
    _check_result_ignored(graph, findings)
    _check_unfenced_mutation(graph, findings)
    _check_compare_direction(graph, findings)
    _check_epoch_threaded(graph, findings)
    if emit_files is not None:
        findings = [f for f in findings if f.path in emit_files]
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
