"""jax-trace-safety: host syncs, tracer branches, retrace hazards in jit.

In the static-bucket decode engine an accidental retrace (or a hidden
host sync) turns a 0.3 ms step into a multi-second stall, and nothing
crashes — it is only visible as tail latency. This checker finds
functions under ``@jax.jit`` / ``pjit`` / ``shard_map`` (as decorators,
``partial(jax.jit, ...)`` decorators, or ``f2 = jax.jit(f)`` wrapping)
and flags, with a light forward taint pass over the function body:

* trace-host-sync      — ``.item()``/``.tolist()``/``block_until_ready``
                         /``jax.device_get``/``np.asarray`` on traced
                         values, ``float()/int()/bool()`` of a traced
                         name.
* trace-python-branch  — ``if``/``while`` whose test uses a traced name
                         directly (``.shape``/``.dtype``/``.ndim``/
                         ``len()``/``is None``/``isinstance`` uses are
                         static and exempt).
* trace-retrace-hazard — a traced name in a shape position
                         (``jnp.zeros(n)``), or iterating a ``set`` while
                         building pytrees (unordered => cache-key churn).

Taint = function parameters (minus ``static_argnums``/``static_argnames``
when they are literals in the ``partial``) plus names assigned from
expressions that use tainted names or call into ``jnp``/``jax.lax``-like
modules. ``x.shape``-style attribute reads are static and un-taint.
Transitively-called package functions get only the unambiguous checks
(``.item()`` etc.) — their parameters may well be static Python values.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.analysis import rules
from ray_tpu.analysis.callgraph import (CallGraph, FunctionInfo, dotted,
                                        _walk_no_nested)
from ray_tpu.analysis.core import Finding

_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "sharding", "names"}
_TRACED_MODULE_HEADS = {"jnp", "jax", "lax", "nn"}


def _is_jit_dotted(d: Optional[str]) -> bool:
    return d is not None and (
        d.split(".")[-1] in rules.JIT_DOTTED_SUFFIXES)


def _has_sharding_kwargs(call: ast.Call) -> bool:
    """True when a call carries in_shardings/out_shardings: a jit-family
    wrapper whatever its name (aliased import, mesh-jit helper) — the
    wrapped function is a trace scope (same hazards as plain jit)."""
    return any(kw.arg in rules.JIT_SHARDING_KWARGS
               for kw in call.keywords)


def _jit_static_params(dec: ast.expr) -> Tuple[bool, Set[int], Set[str]]:
    """(is_jit, static positions, static names) for a decorator expr."""
    if _is_jit_dotted(dotted(dec)):
        return True, set(), set()
    if isinstance(dec, ast.Call):
        d = dotted(dec.func)
        statics_pos: Set[int] = set()
        statics_name: Set[str] = set()
        target = None
        if _is_jit_dotted(d) or _has_sharding_kwargs(dec):
            target = dec
        elif d is not None and d.split(".")[-1] == "partial" and dec.args \
                and (_is_jit_dotted(dotted(dec.args[0]))
                     or _has_sharding_kwargs(dec)):
            target = dec
        if target is not None:
            for kw in target.keywords:
                try:
                    val = ast.literal_eval(kw.value)
                except (ValueError, SyntaxError):
                    continue
                if kw.arg == "static_argnums":
                    vals = val if isinstance(val, (tuple, list)) else [val]
                    statics_pos.update(int(v) for v in vals)
                elif kw.arg == "static_argnames":
                    vals = [val] if isinstance(val, str) else list(val)
                    statics_name.update(vals)
            return True, statics_pos, statics_name
    return False, set(), set()


def _find_jit_functions(graph: CallGraph
                        ) -> Dict[str, Tuple[Set[int], Set[str]]]:
    """fqn -> (static positions, static names) for directly-jitted fns."""
    marked: Dict[str, Tuple[Set[int], Set[str]]] = {}
    for fqn, info in graph.functions.items():
        for dec in getattr(info.node, "decorator_list", []):
            is_jit, pos, names = _jit_static_params(dec)
            if is_jit:
                marked[fqn] = (pos, names)
    # wrapping form: anything(jax.jit(f)) / x = jit(self._step), plus
    # wrappers identified only by their in_shardings/out_shardings
    # kwargs (aliased or helper-built jit — the GSPMD serving idiom).
    for fqn, info in graph.functions.items():
        for node in ast.walk(info.node):
            if not (isinstance(node, ast.Call) and node.args
                    and (_is_jit_dotted(graph.resolved_dotted(node, info))
                         or _has_sharding_kwargs(node))):
                continue
            arg = node.args[0]
            callee = None
            if isinstance(arg, (ast.Name, ast.Attribute)):
                fake = ast.Call(func=arg, args=[], keywords=[])
                ast.copy_location(fake, arg)
                callee, _ = graph.resolve_call(fake, info)
            if callee is not None and callee in graph.functions:
                pos: Set[int] = set()
                names: Set[str] = set()
                for kw in node.keywords:
                    try:
                        val = ast.literal_eval(kw.value)
                    except (ValueError, SyntaxError):
                        continue
                    if kw.arg == "static_argnums":
                        vals = val if isinstance(val, (tuple, list)) \
                            else [val]
                        pos.update(int(v) for v in vals)
                    elif kw.arg == "static_argnames":
                        names.update([val] if isinstance(val, str)
                                     else list(val))
                marked.setdefault(callee, (pos, names))
    return marked


def _numpy_aliases(graph: CallGraph, info: FunctionInfo) -> Set[str]:
    out = set()
    for table in (graph.imports.get(info.module, {}), info.local_imports):
        for name, (kind, target) in table.items():
            if kind == "module" and target == "numpy":
                out.add(name)
    return out


def _taint(info: FunctionInfo, statics: Tuple[Set[int], Set[str]]
           ) -> Set[str]:
    """Forward pass: which local names carry traced values."""
    pos_static, name_static = statics
    args = info.node.args
    params = [a.arg for a in args.posonlyargs + args.args]
    traced: Set[str] = set()
    for i, p in enumerate(params):
        if p in ("self", "cls") or i in pos_static or p in name_static:
            continue
        traced.add(p)
    traced.update(a.arg for a in args.kwonlyargs
                  if a.arg not in name_static)

    def uses_traced(expr: ast.AST) -> bool:
        # Manual walk so `x.shape[0]`-style static reads are PRUNED —
        # the `x` underneath must not taint the assignment target.
        stack = [expr]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
                continue  # static metadata read: don't descend
            if isinstance(n, ast.Call):
                d = dotted(n.func)
                if d is not None and d.split(".")[0] in \
                        _TRACED_MODULE_HEADS:
                    return True
                if d in ("len", "isinstance", "type"):
                    continue  # static: don't descend into the argument
            if isinstance(n, ast.Name) and n.id in traced:
                return True
            stack.extend(ast.iter_child_nodes(n))
        return False

    # two passes to reach a simple fixpoint on straight-line code
    for _ in range(2):
        for node in _walk_no_nested(info.node):
            if isinstance(node, ast.Assign):
                tainted = uses_traced(node.value)
                for tgt in node.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            if tainted:
                                traced.add(n.id)
                            else:
                                traced.discard(n.id)
            elif isinstance(node, ast.AugAssign) and \
                    isinstance(node.target, ast.Name):
                if uses_traced(node.value):
                    traced.add(node.target.id)
    return traced


def _test_traced_names(test: ast.AST, traced: Set[str]) -> List[str]:
    """Traced names used *directly* in a test (static contexts exempt)."""
    static_name_ids: Set[int] = set()
    for n in ast.walk(test):
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
            for sub in ast.walk(n.value):
                if isinstance(sub, ast.Name):
                    static_name_ids.add(id(sub))
        elif isinstance(n, ast.Call):
            d = dotted(n.func)
            if d in ("len", "isinstance", "getattr", "hasattr", "type"):
                for sub in ast.walk(n):
                    if isinstance(sub, ast.Name):
                        static_name_ids.add(id(sub))
        elif isinstance(n, ast.Compare) and any(
                isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops):
            for sub in ast.walk(n):
                if isinstance(sub, ast.Name):
                    static_name_ids.add(id(sub))
    hits = []
    for n in ast.walk(test):
        if isinstance(n, ast.Name) and n.id in traced \
                and id(n) not in static_name_ids:
            hits.append(n.id)
    return hits


def _check_marked(graph: CallGraph, info: FunctionInfo,
                  statics: Tuple[Set[int], Set[str]],
                  findings: List[Finding]) -> None:
    traced = _taint(info, statics)
    np_aliases = _numpy_aliases(graph, info)
    for node in _walk_no_nested(info.node):
        if isinstance(node, ast.Call):
            _check_sync_call(graph, info, node, traced, np_aliases,
                             findings, in_marked=True)
            _check_shape_position(graph, info, node, traced, findings)
        elif isinstance(node, (ast.If, ast.While)):
            hits = _test_traced_names(node.test, traced)
            if hits:
                kind = "while" if isinstance(node, ast.While) else "if"
                findings.append(Finding(
                    rule=rules.TRACE_PY_BRANCH,
                    path=info.file.relpath, line=node.lineno,
                    symbol=info.qualname,
                    message=f"`{kind}` on traced value(s) "
                            f"{sorted(set(hits))} inside jit — use "
                            f"lax.cond/select or hoist to a static "
                            f"argument"))
        elif isinstance(node, ast.For):
            it = node.iter
            is_set = isinstance(it, ast.Set) or (
                isinstance(it, ast.Call) and dotted(it.func) == "set")
            if is_set:
                findings.append(Finding(
                    rule=rules.TRACE_RETRACE,
                    path=info.file.relpath, line=node.lineno,
                    symbol=info.qualname,
                    message="iterating a set inside jit — unordered "
                            "iteration churns the trace cache key"))


def _check_sync_call(graph: CallGraph, info: FunctionInfo, node: ast.Call,
                     traced: Set[str], np_aliases: Set[str],
                     findings: List[Finding], in_marked: bool) -> None:
    path, qn = info.file.relpath, info.qualname

    def add(rule: str, msg: str) -> None:
        findings.append(Finding(rule=rule, path=path, line=node.lineno,
                                symbol=qn, message=msg))

    if isinstance(node.func, ast.Attribute):
        meth = node.func.attr
        if meth in rules.TRACE_SYNC_METHODS:
            add(rules.TRACE_HOST_SYNC,
                f"{rules.TRACE_SYNC_METHODS[meth]} inside jit")
            return
    rd = graph.resolved_dotted(node, info)
    if rd in rules.TRACE_SYNC_DOTTED:
        add(rules.TRACE_HOST_SYNC,
            f"{rules.TRACE_SYNC_DOTTED[rd]} inside jit")
        return
    d = dotted(node.func)
    if d is not None and "." in d:
        head, _, tail = d.partition(".")
        if head in np_aliases and tail in rules.NUMPY_SYNC_FUNCS \
                and node.args and not isinstance(node.args[0],
                                                 ast.Constant):
            add(rules.TRACE_HOST_SYNC,
                f"numpy {tail}() inside jit forces host concretization")
            return
    if in_marked and d in ("float", "int", "bool") and len(node.args) == 1:
        arg = node.args[0]
        names = {n.id for n in ast.walk(arg) if isinstance(n, ast.Name)}
        if names & traced:
            add(rules.TRACE_HOST_SYNC,
                f"{d}() of traced value inside jit is a host sync "
                f"(ConcretizationTypeError under jit)")


def _check_shape_position(graph: CallGraph, info: FunctionInfo,
                          node: ast.Call, traced: Set[str],
                          findings: List[Finding]) -> None:
    d = dotted(node.func)
    if d is None:
        return
    tail = d.split(".")[-1]
    if tail not in rules.SHAPE_POSITION_FUNCS:
        return
    if "." not in d and tail != "reshape":
        return  # bare zeros()/full() etc. unlikely to be jnp
    shape_args: List[ast.AST] = []
    if node.args:
        shape_args.append(node.args[0])
    shape_args.extend(kw.value for kw in node.keywords
                      if kw.arg == "shape")
    for arg in shape_args:
        hits = [n.id for n in ast.walk(arg)
                if isinstance(n, ast.Name) and n.id in traced]
        # x.shape-derived ints are fine; the taint pass already excludes
        # them, so a hit here is a traced VALUE in a shape slot.
        if hits:
            findings.append(Finding(
                rule=rules.TRACE_RETRACE,
                path=info.file.relpath, line=node.lineno,
                symbol=info.qualname,
                message=f"traced value(s) {sorted(set(hits))} in shape "
                        f"position of {tail}() — concretization error or "
                        f"per-value retrace"))
            return


def check(graph: CallGraph, emit_files=None) -> List[Finding]:
    findings: List[Finding] = []

    def in_slice(info: FunctionInfo) -> bool:
        return emit_files is None or info.file.relpath in emit_files

    marked = _find_jit_functions(graph)
    for fqn, statics in marked.items():
        if in_slice(graph.functions[fqn]):
            _check_marked(graph, graph.functions[fqn], statics, findings)
    # transitively jit-reachable: unambiguous host syncs only
    reachable: Set[str] = set()
    queue = list(marked)
    seen: Set[str] = set(queue)
    while queue:
        fqn = queue.pop(0)
        for callee, _line, _vs in graph.edges().get(fqn, ()):
            if callee not in seen:
                seen.add(callee)
                reachable.add(callee)
                queue.append(callee)
    for fqn in reachable:
        if fqn in marked:
            continue
        info = graph.functions[fqn]
        if not in_slice(info):
            continue
        np_aliases = _numpy_aliases(graph, info)
        for node in _walk_no_nested(info.node):
            if isinstance(node, ast.Call):
                _check_sync_call(graph, info, node, set(), np_aliases,
                                 findings, in_marked=False)
    return findings
