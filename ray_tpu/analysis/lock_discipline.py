"""lock-discipline: acquisition-order cycles and blocking under locks.

Builds the package-wide lock graph from ``with <lock>:`` statements
(locks are attributes assigned ``threading.Lock()``/``RLock()``/
``Condition()`` in a class or at module level; ``Condition(self._x)``
aliases to ``_x``). Two rule families:

* lock-order-cycle   — a cycle in the held->acquired edge relation
                       (direct nesting or via resolved package calls) is
                       a deadlock candidate. Self-edges are reported only
                       with same-instance evidence: a ``self.X``
                       (non-reentrant Lock) held while a ``self.``-method
                       chain re-acquires ``self.X``.
* lock-held-blocking — a blocking primitive or RPC verb
                       (``.call``/``.notify``/``ray_tpu.get``/
                       blocking connect/``time.sleep``/unbounded waits)
                       executed, directly or via resolved calls, while a
                       lock is held. Every thread that touches that lock
                       then queues behind the peer's latency.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.analysis import rules
from ray_tpu.analysis.callgraph import (CallGraph, FunctionInfo, dotted,
                                        _short, _walk_no_nested)
from ray_tpu.analysis.core import Finding

_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}


@dataclass(frozen=True)
class LockId:
    module: str
    owner: Optional[str]   # class name, or None for module-level
    attr: str
    kind: str              # lock | rlock | condition

    def label(self) -> str:
        owner = f"{self.owner}." if self.owner else ""
        return f"{self.module.split('.')[-1]}:{owner}{self.attr}"


@dataclass
class Acquisition:
    lock: LockId
    line: int
    via_self: bool
    body: List[ast.stmt]


class LockIndex:
    """All lock declarations in the project, with Condition aliasing.
    Use :func:`lock_index` — the per-graph memo — instead of
    constructing directly (two checker families need it)."""

    def __init__(self, graph: CallGraph):
        self.graph = graph
        # (module, owner-or-None, attr) -> LockId
        self.decls: Dict[Tuple[str, Optional[str], str], LockId] = {}
        # attr name -> [LockId] per module, for obj.attr fallback binding
        self.by_attr: Dict[Tuple[str, str], List[LockId]] = {}
        self._aliases: Dict[Tuple[str, Optional[str], str],
                            Tuple[str, Optional[str], str]] = {}
        for f in graph.project.files:
            self._index_module(f)
        # resolve one level of Condition(self._lock) aliasing
        for key, target in self._aliases.items():
            if target in self.decls and key in self.decls:
                self.decls[key] = self.decls[target]

    def _lock_kind(self, value: ast.AST) -> Optional[str]:
        if isinstance(value, ast.Call):
            d = dotted(value.func)
            if d is not None and d.split(".")[-1] in _LOCK_CTORS \
                    and (d.startswith("threading.")
                         or "." not in d):
                return _LOCK_CTORS[d.split(".")[-1]]
        return None

    def _index_module(self, f) -> None:
        def record(owner: Optional[str], attr: str, value: ast.AST
                   ) -> None:
            kind = self._lock_kind(value)
            if kind is None:
                return
            lock = LockId(f.module, owner, attr, kind)
            key = (f.module, owner, attr)
            self.decls[key] = lock
            self.by_attr.setdefault((f.module, attr), []).append(lock)
            if kind == "condition" and isinstance(value, ast.Call) \
                    and value.args:
                arg = value.args[0]
                if isinstance(arg, ast.Attribute) \
                        and isinstance(arg.value, ast.Name) \
                        and arg.value.id == "self":
                    self._aliases[key] = (f.module, owner, arg.attr)

        # single pass, tracking the innermost enclosing class (the old
        # walk-per-class rescanned nested bodies quadratically);
        # module-level locks are recorded from the top level only, as
        # before
        def visit(node: ast.AST, cls: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name)
                    continue
                if cls is not None and isinstance(child, ast.Assign):
                    for tgt in child.targets:
                        if isinstance(tgt, ast.Attribute) \
                                and isinstance(tgt.value, ast.Name) \
                                and tgt.value.id == "self":
                            record(cls, tgt.attr, child.value)
                        elif isinstance(tgt, ast.Name):
                            record(cls, tgt.id, child.value)
                visit(child, cls)

        visit(f.tree, None)
        for node in f.tree.body:
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        record(None, tgt.id, node.value)

    def bind(self, expr: ast.AST, ctx: FunctionInfo
             ) -> Tuple[Optional[LockId], bool]:
        """Bind a with-item expression to a declared lock."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            base, attr = expr.value.id, expr.attr
            if base in ("self", "cls") and ctx.cls is not None:
                hit = self.decls.get((ctx.module, ctx.cls, attr))
                if hit is not None:
                    return hit, True
                # lock declared on a base/sibling class in this module
                cands = self.by_attr.get((ctx.module, attr), [])
                if len(cands) == 1:
                    return cands[0], True
                return None, False
            # Cls.attr (class-level lock accessed via the class)
            hit = self.decls.get((ctx.module, base, attr))
            if hit is not None:
                return hit, False
            cands = self.by_attr.get((ctx.module, attr), [])
            if len(cands) == 1:
                return cands[0], False
        elif isinstance(expr, ast.Name):
            hit = self.decls.get((ctx.module, None, expr.id))
            if hit is not None:
                return hit, False
        return None, False


def lock_index(graph: CallGraph) -> LockIndex:
    """Per-graph LockIndex memo (lock-discipline and guarded-by both
    need it; indexing the whole package twice showed up in profiles)."""
    cached = getattr(graph, "_lock_index", None)
    if cached is None:
        cached = LockIndex(graph)
        graph._lock_index = cached
    return cached


def _acquisitions(index: LockIndex, info: FunctionInfo
                  ) -> List[Acquisition]:
    """Every bound lock acquisition in the function (nested ``with``
    blocks included, nested defs excluded). Reads the graph's
    withs-by-fqn side index — most functions have no ``with`` at all
    and are skipped without touching their bodies."""
    out: List[Acquisition] = []
    for node in index.graph.withs_by_fqn.get(info.fqn, ()):
        for item in node.items:
            lock, via_self = index.bind(item.context_expr, info)
            if lock is not None:
                out.append(Acquisition(lock, node.lineno, via_self,
                                       node.body))
    return out


def _locks_acquired_closure(graph: CallGraph, index: LockIndex,
                            direct: Dict[str, List[Acquisition]]
                            ) -> Dict[str, Set[Tuple[LockId, bool]]]:
    """fqn -> set of (lock, self_chain) acquired in it or its resolved
    callees. self_chain is True only while every hop is a self.-call and
    the final acquisition is via self (same-instance evidence)."""
    edges: Dict[str, List[Tuple[str, bool]]] = {
        fqn: [(callee, via_self) for callee, _line, via_self in rows]
        for fqn, rows in graph.edges().items()}

    closure: Dict[str, Set[Tuple[LockId, bool]]] = {
        fqn: {(a.lock, a.via_self) for a in acqs}
        for fqn, acqs in direct.items()}
    changed = True
    iters = 0
    while changed and iters < 20:
        changed = False
        iters += 1
        for fqn, outs in edges.items():
            cur = closure[fqn]
            before = len(cur)
            for callee, via_self in outs:
                for lock, self_chain in list(closure.get(callee, ())):
                    cur.add((lock, self_chain and via_self))
            if len(cur) != before:
                changed = True
    return closure


def _blocking_chains(graph: CallGraph) -> Dict[str, List[str]]:
    table = dict(rules.BLOCKING_DOTTED)
    table.update(rules.RPC_DOTTED)
    return graph.blocking_closure(
        table, dict(rules.BLOCKING_METHODS_ALWAYS),
        dict(rules.BLOCKING_METHODS_UNBOUNDED))


def _direct_rpc_sites(graph: CallGraph, info: FunctionInfo
                      ) -> List[Tuple[int, str]]:
    """.call/.notify RPC verbs + resolved RPC dotted names, direct only."""
    sites: List[Tuple[int, str]] = []
    for node in _walk_no_nested(info.node):
        if not isinstance(node, ast.Call):
            continue
        rd = graph.resolved_dotted(node, info)
        if rd is not None and rd in rules.RPC_DOTTED:
            sites.append((node.lineno, f"{rd} ({rules.RPC_DOTTED[rd]})"))
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in rules.RPC_METHODS:
            sites.append((node.lineno,
                          f".{node.func.attr}() "
                          f"({rules.RPC_METHODS[node.func.attr]})"))
    return sites


def check(graph: CallGraph, emit_files=None) -> List[Finding]:
    index = lock_index(graph)
    findings: List[Finding] = []
    chains = _blocking_chains(graph)
    direct_acqs: Dict[str, List[Acquisition]] = {
        fqn: _acquisitions(index, info)
        for fqn, info in graph.functions.items()}
    closure = _locks_acquired_closure(graph, index, direct_acqs)

    # fqn -> [(line, label)] for direct blocking sites (lock table: no
    # file I/O — serializing a file write is often the lock's purpose).
    lock_dotted = dict(rules.BLOCKING_DOTTED)
    lock_dotted.update(rules.RPC_DOTTED)

    edge_sites: Dict[Tuple[LockId, LockId], Tuple[str, int]] = {}
    edges: Dict[LockId, Set[LockId]] = {}
    self_edges: List[Tuple[LockId, str, int]] = []

    for fqn, info in graph.functions.items():
        # ordering edges are whole-program (a cycle can span files); only
        # the per-site blocking findings are sliceable
        emit_here = emit_files is None \
            or info.file.relpath in emit_files
        for acq in direct_acqs[fqn]:
            held = acq.lock
            # -------- blocking under the lock (direct statements)
            for node in _iter_body(acq.body):
                if not isinstance(node, ast.Call):
                    continue
                label = _blocking_label(graph, info, node, lock_dotted)
                if label is not None and not emit_here:
                    continue
                if label is not None:
                    findings.append(Finding(
                        rule=rules.LOCK_HELD_BLOCKING,
                        path=info.file.relpath, line=node.lineno,
                        symbol=info.qualname,
                        message=f"{label} while holding "
                                f"{held.label()}"))
                    continue
                callee, via_self = graph.resolve_call_cached(node, info)
                if callee is not None and callee in chains \
                        and emit_here:
                    chain = " -> ".join(chains[callee])
                    findings.append(Finding(
                        rule=rules.LOCK_HELD_BLOCKING,
                        path=info.file.relpath, line=node.lineno,
                        symbol=info.qualname,
                        message=f"call into blocking {_short(callee)} "
                                f"({chain}) while holding "
                                f"{held.label()}"))
                # -------- ordering edges via calls
                if callee is not None:
                    for lock, self_chain in closure.get(callee, ()):
                        if lock == held:
                            if self_chain and via_self and acq.via_self \
                                    and held.kind == "lock":
                                self_edges.append(
                                    (held, info.qualname, node.lineno))
                            continue
                        edges.setdefault(held, set()).add(lock)
                        edge_sites.setdefault(
                            (held, lock),
                            (f"{info.file.relpath}:{node.lineno} "
                             f"({info.qualname} -> {_short(callee)})",
                             node.lineno))
            # -------- ordering edges via direct nesting
            for inner in _nested_acquisitions(index, info, acq.body):
                if inner.lock == held:
                    if inner.via_self and acq.via_self \
                            and held.kind == "lock":
                        self_edges.append(
                            (held, info.qualname, inner.line))
                    continue
                edges.setdefault(held, set()).add(inner.lock)
                edge_sites.setdefault(
                    (held, inner.lock),
                    (f"{info.file.relpath}:{inner.line} "
                     f"({info.qualname})", inner.line))

    # -------- cycles (length >= 2) via DFS
    for cycle in _find_cycles(edges):
        a, b = cycle[0], cycle[1 % len(cycle)]
        site, line = edge_sites.get((a, b), ("?", 0))
        info_file, qn = _site_owner(graph, site)
        findings.append(Finding(
            rule=rules.LOCK_ORDER_CYCLE,
            path=info_file or "ray_tpu", line=line, symbol=qn,
            message="lock-order cycle (deadlock candidate): "
                    + " -> ".join(lk.label() for lk in cycle)
                    + f" -> {cycle[0].label()}; first edge at {site}"))
    for held, qn, line in self_edges:
        owner_file = graph.project.by_module[held.module].relpath
        findings.append(Finding(
            rule=rules.LOCK_ORDER_CYCLE,
            path=owner_file, line=line, symbol=qn,
            message=f"re-acquisition of non-reentrant {held.label()} on "
                    f"the same instance via a self.-call chain "
                    f"(self-deadlock)"))
    if emit_files is not None:
        findings = [f for f in findings if f.path in emit_files]
    return findings


def _blocking_label(graph: CallGraph, info: FunctionInfo, node: ast.Call,
                    lock_dotted: Dict[str, str]) -> Optional[str]:
    rd = graph.resolved_dotted(node, info)
    if rd is not None and rd in lock_dotted:
        return f"{rd} ({lock_dotted[rd]})"
    if isinstance(node.func, ast.Attribute):
        meth = node.func.attr
        if meth in rules.RPC_METHODS:
            return f".{meth}() ({rules.RPC_METHODS[meth]})"
        if meth in rules.BLOCKING_METHODS_ALWAYS:
            return f".{meth}() ({rules.BLOCKING_METHODS_ALWAYS[meth]})"
        if meth in rules.BLOCKING_METHODS_UNBOUNDED and not node.args \
                and not node.keywords:
            return f".{meth}() ({rules.BLOCKING_METHODS_UNBOUNDED[meth]})"
    return None


def _iter_body(stmts: List[ast.stmt]):
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _nested_acquisitions(index: LockIndex, info: FunctionInfo,
                         body: List[ast.stmt]) -> List[Acquisition]:
    out: List[Acquisition] = []
    for node in _iter_body(body):
        if isinstance(node, ast.With):
            for item in node.items:
                lock, via_self = index.bind(item.context_expr, info)
                if lock is not None:
                    out.append(Acquisition(lock, node.lineno, via_self,
                                           node.body))
    return out


def _find_cycles(edges: Dict[LockId, Set[LockId]]) -> List[List[LockId]]:
    """Simple cycle enumeration, deduped by cycle node-set."""
    cycles: List[List[LockId]] = []
    seen_sets: Set[frozenset] = set()

    def dfs(start: LockId, node: LockId, path: List[LockId],
            on_path: Set[LockId]) -> None:
        for nxt in edges.get(node, ()):
            if nxt == start and len(path) >= 2:
                key = frozenset(path)
                if key not in seen_sets:
                    seen_sets.add(key)
                    cycles.append(list(path))
            elif nxt not in on_path and len(path) < 6:
                on_path.add(nxt)
                dfs(start, nxt, path + [nxt], on_path)
                on_path.discard(nxt)

    for start in list(edges):
        dfs(start, start, [start], {start})
    return cycles


def _site_owner(graph: CallGraph, site: str) -> Tuple[Optional[str], str]:
    path = site.split(":", 1)[0] if ":" in site else None
    qn = site.split("(")[-1].rstrip(")") if "(" in site else "<module>"
    return path, qn.split(" ->")[0].strip()
