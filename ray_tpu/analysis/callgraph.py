"""Best-effort intra-package call graph over the project ASTs.

Resolution is deliberately conservative: a call we cannot bind to a
package function is simply not an edge (checkers treat unresolved calls
as opaque). What IS resolved:

* ``foo(...)``            — module function / class in the same module,
                            or a ``from x import foo`` target.
* ``mod.foo(...)``        — where ``mod``/alias binds an imported module
                            (``import ray_tpu.core.rpc as rpc``).
* ``self.meth(...)``      — method of the enclosing class (single-module
                            base-class walk included).
* ``Cls(...)``            — constructor => ``Cls.__init__``.
* ``obj.meth(...)``       — when exactly one class in the same module
                            defines ``meth`` (covers the ``st: _Conn``
                            pattern in core/rpc.py).
* ``f = self.foo; f()``   — bound-method aliasing through simple local
                            assignments (last assignment wins).
* ``functools.partial(self.foo, x)(...)`` — unwrapped to its target,
                            including aliased partials.
* ``self.pubsub.poll(...)`` — one level of self-attribute typing:
                            ``self.pubsub = Pubsub()`` in any method of
                            the class binds the attribute's class, so
                            calls through it resolve cross-module.

Decorated functions need no special casing — the AST name still binds
the undecorated ``FunctionDef``, so call edges into them resolve exactly
like plain functions (fixture-tested in tests/test_analysis_v2.py).

Imports are collected at module level AND inside each function (this
codebase imports locally for cycle-avoidance all over).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.analysis.core import Project, SourceFile

# Names that collide with builtin container/str methods: the
# single-owner-in-module fallback must never bind `msg.get(...)` or
# `buf.append(...)` to a package method that happens to share the name.
_BUILTIN_METHODS: Set[str] = set()
for _t in (dict, list, set, str, bytes, bytearray, tuple, frozenset):
    _BUILTIN_METHODS.update(n for n in dir(_t) if not n.startswith("__"))


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class FunctionInfo:
    fqn: str                     # "ray_tpu.core.rpc:RpcServer._flush"
    module: str
    qualname: str
    cls: Optional[str]
    node: ast.AST                # FunctionDef / AsyncFunctionDef
    file: SourceFile
    local_imports: Dict[str, Tuple[str, Optional[str]]] = \
        field(default_factory=dict)
    # local name -> aliased callable expr (``f = self.foo`` /
    # ``f = functools.partial(self.foo, x)``); last assignment wins.
    aliases: Dict[str, ast.AST] = field(default_factory=dict)


@dataclass
class ClassInfo:
    module: str
    name: str
    node: ast.ClassDef
    methods: Dict[str, str] = field(default_factory=dict)  # name -> fqn
    bases: List[str] = field(default_factory=list)


class CallGraph:
    def __init__(self, project: Project, package: str = "ray_tpu"):
        self.project = project
        self.package = package
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[Tuple[str, str], ClassInfo] = {}
        # module -> {local name -> (kind, target)}; kind "module" binds a
        # module path, kind "object" binds (module path, attr name).
        self.imports: Dict[str, Dict[str, Tuple[str, Optional[str]]]] = {}
        # module -> method name -> [class names defining it]
        self._method_owners: Dict[str, Dict[str, List[str]]] = {}
        # (module, cls, attr) -> (target module, target class): the type
        # of ``self.attr`` when some method assigns
        # ``self.attr = Cls(...)`` with Cls a package class.
        self.self_attr_types: Dict[Tuple[str, str, str],
                                   Tuple[str, str]] = {}
        self._self_attr_candidates: List[Tuple[FunctionInfo, ast.AST]] = []
        for f in project.files:
            self._index_file(f)
        self._index_self_attr_types()
        self._self_attr_candidates = []
        self._edges: Optional[Dict[str, List[Tuple[str, int, bool]]]] = \
            None
        self._call_targets: Dict[int, Tuple[str, bool]] = {}
        # side indexes built during the edges() walk (one body pass
        # serves every checker): call tail name -> [(node, info)],
        # keyword-arg name -> [(node, info)], attribute-target
        # AugAssigns, and fqn -> [With] (nested ones included)
        self.calls_by_tail: Dict[str,
                                 List[Tuple[ast.Call, FunctionInfo]]] = {}
        self.calls_by_kwarg: Dict[str,
                                  List[Tuple[ast.Call, FunctionInfo]]] = {}
        self.attr_augassigns: List[Tuple[ast.AugAssign, FunctionInfo]] = []
        self.withs_by_fqn: Dict[str, List[ast.With]] = {}

    def edges(self) -> Dict[str, List[Tuple[str, int, bool]]]:
        """fqn -> [(callee fqn, line, via_self)] for every resolved
        intra-package call, computed once and shared by all checkers
        (resolve_call is the analyzer's hottest path)."""
        if self._edges is None:
            out: Dict[str, List[Tuple[str, int, bool]]] = {}
            for fqn, info in self.functions.items():
                rows: List[Tuple[str, int, bool]] = []
                for node in _walk_no_nested(info.node):
                    if isinstance(node, ast.Call):
                        res = self.resolve_call(node, info)
                        self._call_targets[id(node)] = res
                        callee, via_self = res
                        if callee is not None \
                                and callee in self.functions:
                            rows.append((callee, node.lineno, via_self))
                        func = node.func
                        tail = func.attr \
                            if isinstance(func, ast.Attribute) else (
                                func.id if isinstance(func, ast.Name)
                                else None)
                        if tail is not None:
                            self.calls_by_tail.setdefault(
                                tail, []).append((node, info))
                        for kw in node.keywords:
                            if kw.arg is not None:
                                self.calls_by_kwarg.setdefault(
                                    kw.arg, []).append((node, info))
                    elif isinstance(node, ast.AugAssign) \
                            and isinstance(node.target, ast.Attribute):
                        self.attr_augassigns.append((node, info))
                    elif isinstance(node, (ast.With, ast.AsyncWith)):
                        self.withs_by_fqn.setdefault(fqn, []).append(
                            node)
                out[fqn] = rows
            self._edges = out
        return self._edges

    def resolve_call_cached(self, call: ast.Call, ctx: FunctionInfo
                            ) -> Tuple[Optional[str], bool]:
        """resolve_call through the edges() cache (same AST objects, so
        node identity keys it); falls back to a live resolve for nodes
        outside any indexed function body."""
        if self._edges is None:
            self.edges()
        hit = self._call_targets.get(id(call))
        if hit is not None:
            return hit
        return self.resolve_call(call, ctx)

    # ------------------------------------------------------------ indexing

    def _index_file(self, f: SourceFile) -> None:
        """Single pass over the module tree: imports, classes, methods,
        aliases and self-attr assigns are collected as each node is
        first visited (re-walking every function body for each concern
        made indexing the analyzer's hottest path)."""
        imports: Dict[str, Tuple[str, Optional[str]]] = {}
        self.imports[f.module] = imports
        owners: Dict[str, List[str]] = {}
        self._method_owners[f.module] = owners

        def add_import(child: ast.AST,
                       into: Dict[str, Tuple[str, Optional[str]]]
                       ) -> None:
            if isinstance(child, ast.Import):
                for alias in child.names:
                    name = alias.asname or alias.name.split(".")[0]
                    target = alias.name if alias.asname else \
                        alias.name.split(".")[0]
                    into[name] = ("module", target)
            elif isinstance(child, ast.ImportFrom) and child.module:
                for alias in child.names:
                    into[alias.asname or alias.name] = (
                        "object", f"{child.module}.{alias.name}")

        def visit(node: ast.AST, stack: List[ast.AST],
                  cls: Optional[str],
                  fn_info: Optional[FunctionInfo]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.Import, ast.ImportFrom)):
                    # function-local imports bind locally; everything
                    # else (module/class level, and also inside nested
                    # scopes' enclosing function) binds to the nearest
                    # function, falling back to the module table
                    add_import(child, fn_info.local_imports
                               if fn_info is not None else imports)
                    continue
                if isinstance(child, ast.ClassDef):
                    ci = ClassInfo(f.module, child.name, child,
                                   bases=[d for d in
                                          (dotted(b) for b in child.bases)
                                          if d])
                    self.classes[(f.module, child.name)] = ci
                    visit(child, stack + [child], child.name, fn_info)
                    continue
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qn_parts = [n.name for n in stack
                                if isinstance(n, (ast.ClassDef,
                                                  ast.FunctionDef,
                                                  ast.AsyncFunctionDef))]
                    qn = ".".join(qn_parts + [child.name])
                    fqn = f"{f.module}:{qn}"
                    info = FunctionInfo(fqn, f.module, qn, cls, child, f)
                    self.functions[fqn] = info
                    if cls is not None and len(qn_parts) == 1:
                        self.classes[(f.module, cls)].methods[
                            child.name] = fqn
                        owners.setdefault(child.name, []).append(cls)
                    # nested defs: indexed but rarely resolved into
                    visit(child, stack + [child], cls, info)
                    continue
                if fn_info is not None and isinstance(child, ast.Assign) \
                        and len(child.targets) == 1 \
                        and isinstance(child.targets[0], ast.Name) \
                        and isinstance(child.value, (ast.Attribute,
                                                     ast.Name, ast.Call)):
                    # Callable-shaped alias values only: bound methods /
                    # functions (Attribute, Name) and partial
                    # constructions (Call — harmless for other calls:
                    # resolution of ``x = foo(); x()`` just fails at the
                    # non-partial Call).
                    fn_info.aliases[child.targets[0].id] = child.value
                if fn_info is not None and fn_info.cls is not None \
                        and isinstance(child, (ast.Assign,
                                               ast.AnnAssign)):
                    self._self_attr_candidates.append((fn_info, child))
                visit(child, stack, cls, fn_info)

        visit(f.tree, [], None, None)
        # A name imported inside any function remains a resolution
        # fallback module-wide (this file's historical behavior — local
        # import tables win, the module table catches the rest).
        for key, info in self.functions.items():
            if info.file is f:
                for name, target in info.local_imports.items():
                    imports.setdefault(name, target)

    # ---------------------------------------------------------- resolution

    def _import_target(self, ctx: FunctionInfo, name: str
                       ) -> Optional[Tuple[str, Optional[str]]]:
        hit = ctx.local_imports.get(name)
        if hit is None:
            hit = self.imports.get(ctx.module, {}).get(name)
        return hit

    def _module_symbol(self, module: str, name: str) -> Optional[str]:
        """fqn of function `name` or class-constructor in `module`."""
        fqn = f"{module}:{name}"
        if fqn in self.functions:
            return fqn
        ci = self.classes.get((module, name))
        if ci is not None:
            init = ci.methods.get("__init__")
            return init if init is not None else fqn  # class w/o __init__
        return None

    def _class_method(self, module: str, cls: str, meth: str,
                      depth: int = 0) -> Optional[str]:
        ci = self.classes.get((module, cls))
        if ci is None or depth > 4:
            return None
        fqn = ci.methods.get(meth)
        if fqn is not None:
            return fqn
        for base in ci.bases:
            base = base.split(".")[-1]
            hit = self._class_method(module, base, meth, depth + 1)
            if hit is not None:
                return hit
        return None

    def _index_self_attr_types(self) -> None:
        """``self.attr = Cls(...)`` (and ``self.attr: Cls`` /
        ``Optional[Cls]`` annotations) in any method of a class bind the
        attribute's type; conflicting assignments poison the entry.
        Candidates were collected during the single indexing pass."""
        poisoned: Set[Tuple[str, str, str]] = set()
        for info, node in self._self_attr_candidates:
            tgt = val_cls = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(node.value, ast.Call):
                    val_cls = self._class_of_ctor(node.value, info)
            elif isinstance(node, ast.AnnAssign):
                tgt = node.target
                val_cls = self._class_of_annotation(
                    node.annotation, info)
                if isinstance(node.value, ast.Call) and val_cls is None:
                    val_cls = self._class_of_ctor(node.value, info)
            if val_cls is None or not isinstance(tgt, ast.Attribute) \
                    or not isinstance(tgt.value, ast.Name) \
                    or tgt.value.id != "self":
                continue
            key = (info.module, info.cls, tgt.attr)
            old = self.self_attr_types.get(key)
            if old is not None and old != val_cls:
                poisoned.add(key)
            else:
                self.self_attr_types[key] = val_cls
        for key in poisoned:
            self.self_attr_types.pop(key, None)

    def _class_of_ctor(self, call: ast.Call, ctx: FunctionInfo
                       ) -> Optional[Tuple[str, str]]:
        func = call.func
        if isinstance(func, ast.Name):
            if (ctx.module, func.id) in self.classes:
                return (ctx.module, func.id)
            imp = self._import_target(ctx, func.id)
            if imp is not None and imp[0] == "object" and imp[1] \
                    and imp[1].startswith(self.package):
                mod, _, attr = imp[1].rpartition(".")
                if (mod, attr) in self.classes:
                    return (mod, attr)
        elif isinstance(func, ast.Attribute):
            d = self.resolved_dotted(call, ctx)
            if d:
                mod, _, attr = d.rpartition(".")
                if (mod, attr) in self.classes:
                    return (mod, attr)
        return None

    def _class_of_annotation(self, ann: ast.AST, ctx: FunctionInfo
                             ) -> Optional[Tuple[str, str]]:
        # Optional[X] / "X" string forms unwrap to X where recognizable.
        if isinstance(ann, ast.Subscript):
            d = dotted(ann.value)
            if d is not None and d.split(".")[-1] == "Optional":
                return self._class_of_annotation(ann.slice, ctx)
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str) \
                and ann.value.isidentifier():
            ann = ast.Name(id=ann.value)
        if isinstance(ann, ast.Name):
            if (ctx.module, ann.id) in self.classes:
                return (ctx.module, ann.id)
            imp = self._import_target(ctx, ann.id)
            if imp is not None and imp[0] == "object" and imp[1] \
                    and imp[1].startswith(self.package):
                mod, _, attr = imp[1].rpartition(".")
                if (mod, attr) in self.classes:
                    return (mod, attr)
        return None

    def _is_partial_ctor(self, call: ast.Call, ctx: FunctionInfo) -> bool:
        d = self.resolved_dotted(call, ctx)
        return d is not None and d.split(".")[-1] == "partial" \
            and bool(call.args)

    def expr_is_self_bound(self, expr: ast.AST, ctx: FunctionInfo,
                           depth: int = 0) -> bool:
        """True when calling ``expr`` runs a method on THIS instance
        (``self.foo``, an alias of it, or a partial over it)."""
        if depth > 3:
            return False
        if isinstance(expr, ast.Attribute):
            return isinstance(expr.value, ast.Name) \
                and expr.value.id in ("self", "cls")
        if isinstance(expr, ast.Name):
            alias = ctx.aliases.get(expr.id)
            if alias is not None and alias is not expr:
                return self.expr_is_self_bound(alias, ctx, depth + 1)
        if isinstance(expr, ast.Call) and self._is_partial_ctor(expr, ctx):
            return self.expr_is_self_bound(expr.args[0], ctx, depth + 1)
        return False

    def resolve_callable_expr(self, expr: ast.AST, ctx: FunctionInfo,
                              depth: int = 0) -> Optional[str]:
        """Resolve an expression used as a callable (a call's func, a
        thread/executor target, a handler value, an aliased local) to a
        package function fqn, or None."""
        if expr is None or depth > 3:
            return None
        if isinstance(expr, ast.Call):
            # functools.partial(target, ...) resolves to its target;
            # any other call-result callable is opaque.
            if self._is_partial_ctor(expr, ctx):
                return self.resolve_callable_expr(expr.args[0], ctx,
                                                  depth + 1)
            return None
        if isinstance(expr, ast.Name):
            name = expr.id
            hit = self._module_symbol(ctx.module, name)
            if hit is not None:
                return hit
            imp = self._import_target(ctx, name)
            if imp is not None:
                kind, target = imp
                if kind == "object" and target and \
                        target.startswith(self.package):
                    mod, _, attr = target.rpartition(".")
                    if mod in self.project.by_module:
                        return self._module_symbol(mod, attr)
                return None
            alias = ctx.aliases.get(name)
            if alias is not None and alias is not expr:
                return self.resolve_callable_expr(alias, ctx, depth + 1)
            return None
        if isinstance(expr, ast.Attribute):
            recv, meth = expr.value, expr.attr
            if isinstance(recv, ast.Name):
                if recv.id in ("self", "cls") and ctx.cls is not None:
                    return self._class_method(ctx.module, ctx.cls, meth)
                imp = self._import_target(ctx, recv.id)
                if imp is not None and imp[0] == "module" and \
                        imp[1].startswith(self.package) and \
                        imp[1] in self.project.by_module:
                    return self._module_symbol(imp[1], meth)
                # Cls.method(...) in the same module
                if (ctx.module, recv.id) in self.classes:
                    return self._class_method(ctx.module, recv.id, meth)
                # obj.meth for a bare-name receiver, when exactly one
                # class in this module defines meth — covers the
                # ``st: _Conn`` pattern. Never for names shared with
                # builtin container/str methods (msg.get, buf.append...).
                if meth not in _BUILTIN_METHODS and meth != "__init__":
                    owners = self._method_owners.get(ctx.module, {}).get(
                        meth, [])
                    if len(owners) == 1:
                        return self._class_method(ctx.module, owners[0],
                                                  meth)
                return None
            if isinstance(recv, ast.Attribute) \
                    and isinstance(recv.value, ast.Name) \
                    and recv.value.id in ("self", "cls") \
                    and ctx.cls is not None:
                # self.attr.meth via self-attribute typing
                typ = self.self_attr_types.get(
                    (ctx.module, ctx.cls, recv.attr))
                if typ is not None:
                    return self._class_method(typ[0], typ[1], meth)
            d = dotted(expr)
            if d is not None and d.startswith(self.package + "."):
                mod, _, attr = d.rpartition(".")
                if mod in self.project.by_module:
                    return self._module_symbol(mod, attr)
        return None

    def resolve_call(self, call: ast.Call, ctx: FunctionInfo
                     ) -> Tuple[Optional[str], bool]:
        """-> (callee fqn or None, via_self). via_self is True only for
        direct/aliased calls on THIS instance (self-deadlock evidence) —
        not for calls through a typed self-attribute, whose locks belong
        to a different object."""
        return (self.resolve_callable_expr(call.func, ctx),
                self.expr_is_self_bound(call.func, ctx))

    def resolved_dotted(self, call: ast.Call, ctx: FunctionInfo
                        ) -> Optional[str]:
        """Dotted name with the leading import alias normalized to its
        real module path (``sleep`` -> ``time.sleep`` for
        ``from time import sleep``)."""
        d = dotted(call.func)
        if d is None:
            return None
        head, _, rest = d.partition(".")
        imp = self._import_target(ctx, head)
        if imp is None:
            return d
        kind, target = imp
        if kind == "module":
            return f"{target}.{rest}" if rest else target
        return f"{target}.{rest}" if rest else target

    # ------------------------------------------------- blocking analysis

    def direct_blocking_map(self, dotted_table: Dict[str, str],
                            methods_always: Dict[str, str],
                            methods_unbounded: Dict[str, str],
                            ) -> Dict[str, List[Tuple[int, str]]]:
        """fqn -> (line, label) for every blocking primitive called
        directly in it (nested defs excluded — they run later). Built
        from the calls-by-tail side index: only calls whose trailing
        name can possibly match a table entry are resolved."""
        self.edges()
        sites: Dict[str, List[Tuple[int, str]]] = {}

        tails = {d.split(".")[-1] for d in dotted_table}
        for tail in tails:
            for node, info in self.calls_by_tail.get(tail, ()):
                rd = self.resolved_dotted(node, info)
                if rd is not None and rd in dotted_table:
                    sites.setdefault(info.fqn, []).append(
                        (node.lineno, f"{rd} ({dotted_table[rd]})"))
        for meth, label in methods_always.items():
            for node, info in self.calls_by_tail.get(meth, ()):
                if isinstance(node.func, ast.Attribute):
                    rd = self.resolved_dotted(node, info)
                    if rd is not None and rd in dotted_table:
                        continue  # already counted via the dotted table
                    sites.setdefault(info.fqn, []).append(
                        (node.lineno, f".{meth}() ({label})"))
        for meth, label in methods_unbounded.items():
            for node, info in self.calls_by_tail.get(meth, ()):
                if isinstance(node.func, ast.Attribute) \
                        and not node.args and not node.keywords:
                    sites.setdefault(info.fqn, []).append(
                        (node.lineno, f".{meth}() ({label})"))
        for rows in sites.values():
            rows.sort()
        return sites

    def blocking_closure(self, dotted_table: Dict[str, str],
                         methods_always: Dict[str, str],
                         methods_unbounded: Dict[str, str],
                         ) -> Dict[str, List[str]]:
        """fqn -> shortest call chain (list of labels) ending at a
        blocking primitive, for every transitively-blocking function."""
        all_edges = self.edges()
        direct: Dict[str, List[Tuple[int, str]]] = self.direct_blocking_map(
            dotted_table, methods_always, methods_unbounded)
        direct = {fqn: direct.get(fqn, []) for fqn in self.functions}
        edges: Dict[str, List[Tuple[str, int]]] = {
            fqn: [(callee, line) for callee, line, _ in rows]
            for fqn, rows in all_edges.items()}

        chains: Dict[str, List[str]] = {}
        for fqn, sites in direct.items():
            if sites:
                line, label = sites[0]
                chains[fqn] = [f"{_short(fqn)}:{line} -> {label}"]
        # BFS fixpoint: propagate the shortest chain to callers.
        changed = True
        while changed:
            changed = False
            for fqn, outs in edges.items():
                if fqn in chains:
                    continue
                for callee, line in outs:
                    if callee in chains:
                        chains[fqn] = (
                            [f"{_short(fqn)}:{line}"] + chains[callee])
                        changed = True
                        break
        return chains


def _walk_no_nested(fn_node: ast.AST):
    """Walk a function body without descending into nested defs/classes
    (those execute on their own schedule, not in this frame)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _short(fqn: str) -> str:
    return fqn.split(":", 1)[-1]
