"""Best-effort intra-package call graph over the project ASTs.

Resolution is deliberately conservative: a call we cannot bind to a
package function is simply not an edge (checkers treat unresolved calls
as opaque). What IS resolved:

* ``foo(...)``            — module function / class in the same module,
                            or a ``from x import foo`` target.
* ``mod.foo(...)``        — where ``mod``/alias binds an imported module
                            (``import ray_tpu.core.rpc as rpc``).
* ``self.meth(...)``      — method of the enclosing class (single-module
                            base-class walk included).
* ``Cls(...)``            — constructor => ``Cls.__init__``.
* ``obj.meth(...)``       — when exactly one class in the same module
                            defines ``meth`` (covers the ``st: _Conn``
                            pattern in core/rpc.py).

Imports are collected at module level AND inside each function (this
codebase imports locally for cycle-avoidance all over).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.analysis.core import Project, SourceFile

# Names that collide with builtin container/str methods: the
# single-owner-in-module fallback must never bind `msg.get(...)` or
# `buf.append(...)` to a package method that happens to share the name.
_BUILTIN_METHODS: Set[str] = set()
for _t in (dict, list, set, str, bytes, bytearray, tuple, frozenset):
    _BUILTIN_METHODS.update(n for n in dir(_t) if not n.startswith("__"))


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class FunctionInfo:
    fqn: str                     # "ray_tpu.core.rpc:RpcServer._flush"
    module: str
    qualname: str
    cls: Optional[str]
    node: ast.AST                # FunctionDef / AsyncFunctionDef
    file: SourceFile
    local_imports: Dict[str, Tuple[str, Optional[str]]] = \
        field(default_factory=dict)


@dataclass
class ClassInfo:
    module: str
    name: str
    node: ast.ClassDef
    methods: Dict[str, str] = field(default_factory=dict)  # name -> fqn
    bases: List[str] = field(default_factory=list)


class CallGraph:
    def __init__(self, project: Project, package: str = "ray_tpu"):
        self.project = project
        self.package = package
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[Tuple[str, str], ClassInfo] = {}
        # module -> {local name -> (kind, target)}; kind "module" binds a
        # module path, kind "object" binds (module path, attr name).
        self.imports: Dict[str, Dict[str, Tuple[str, Optional[str]]]] = {}
        # module -> method name -> [class names defining it]
        self._method_owners: Dict[str, Dict[str, List[str]]] = {}
        for f in project.files:
            self._index_file(f)

    # ------------------------------------------------------------ indexing

    def _index_file(self, f: SourceFile) -> None:
        imports: Dict[str, Tuple[str, Optional[str]]] = {}
        self.imports[f.module] = imports
        owners: Dict[str, List[str]] = {}
        self._method_owners[f.module] = owners

        def collect_imports(node: ast.AST,
                            into: Dict[str, Tuple[str, Optional[str]]]
                            ) -> None:
            for child in ast.walk(node):
                if isinstance(child, ast.Import):
                    for alias in child.names:
                        name = alias.asname or alias.name.split(".")[0]
                        target = alias.name if alias.asname else \
                            alias.name.split(".")[0]
                        into[name] = ("module", target)
                elif isinstance(child, ast.ImportFrom) and child.module:
                    for alias in child.names:
                        into[alias.asname or alias.name] = (
                            "object", f"{child.module}.{alias.name}")

        collect_imports(f.tree, imports)

        def visit(node: ast.AST, stack: List[ast.AST],
                  cls: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    ci = ClassInfo(f.module, child.name, child,
                                   bases=[d for d in
                                          (dotted(b) for b in child.bases)
                                          if d])
                    self.classes[(f.module, child.name)] = ci
                    visit(child, stack + [child], child.name)
                elif isinstance(child,
                                (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qn_parts = [n.name for n in stack
                                if isinstance(n, (ast.ClassDef,
                                                  ast.FunctionDef,
                                                  ast.AsyncFunctionDef))]
                    qn = ".".join(qn_parts + [child.name])
                    fqn = f"{f.module}:{qn}"
                    info = FunctionInfo(fqn, f.module, qn, cls, child, f)
                    collect_imports(child, info.local_imports)
                    self.functions[fqn] = info
                    if cls is not None and len(qn_parts) == 1:
                        self.classes[(f.module, cls)].methods[
                            child.name] = fqn
                        owners.setdefault(child.name, []).append(cls)
                    # nested defs: indexed but rarely resolved into
                    visit(child, stack + [child], cls)

        visit(f.tree, [], None)

    # ---------------------------------------------------------- resolution

    def _import_target(self, ctx: FunctionInfo, name: str
                       ) -> Optional[Tuple[str, Optional[str]]]:
        hit = ctx.local_imports.get(name)
        if hit is None:
            hit = self.imports.get(ctx.module, {}).get(name)
        return hit

    def _module_symbol(self, module: str, name: str) -> Optional[str]:
        """fqn of function `name` or class-constructor in `module`."""
        fqn = f"{module}:{name}"
        if fqn in self.functions:
            return fqn
        ci = self.classes.get((module, name))
        if ci is not None:
            init = ci.methods.get("__init__")
            return init if init is not None else fqn  # class w/o __init__
        return None

    def _class_method(self, module: str, cls: str, meth: str,
                      depth: int = 0) -> Optional[str]:
        ci = self.classes.get((module, cls))
        if ci is None or depth > 4:
            return None
        fqn = ci.methods.get(meth)
        if fqn is not None:
            return fqn
        for base in ci.bases:
            base = base.split(".")[-1]
            hit = self._class_method(module, base, meth, depth + 1)
            if hit is not None:
                return hit
        return None

    def resolve_call(self, call: ast.Call, ctx: FunctionInfo
                     ) -> Tuple[Optional[str], bool]:
        """-> (callee fqn or None, via_self)."""
        func = call.func
        if isinstance(func, ast.Name):
            name = func.id
            hit = self._module_symbol(ctx.module, name)
            if hit is not None:
                return hit, False
            imp = self._import_target(ctx, name)
            if imp is not None:
                kind, target = imp
                if kind == "object" and target and \
                        target.startswith(self.package):
                    mod, _, attr = target.rpartition(".")
                    if mod in self.project.by_module:
                        return self._module_symbol(mod, attr), False
            return None, False
        if isinstance(func, ast.Attribute):
            recv, meth = func.value, func.attr
            if isinstance(recv, ast.Name):
                if recv.id in ("self", "cls") and ctx.cls is not None:
                    return (self._class_method(ctx.module, ctx.cls, meth),
                            True)
                imp = self._import_target(ctx, recv.id)
                if imp is not None and imp[0] == "module" and \
                        imp[1].startswith(self.package) and \
                        imp[1] in self.project.by_module:
                    return self._module_symbol(imp[1], meth), False
                # Cls.method(...) in the same module
                if (ctx.module, recv.id) in self.classes:
                    return (self._class_method(ctx.module, recv.id, meth),
                            False)
                # obj.meth for a bare-name receiver, when exactly one
                # class in this module defines meth — covers the
                # ``st: _Conn`` parameter pattern. Never for names shared
                # with builtin container/str methods (msg.get,
                # queue.popleft, buf.append...), and never for dotted
                # receivers (self._cond.wait) whose type is unknowable.
                if meth not in _BUILTIN_METHODS and meth != "__init__":
                    owners = self._method_owners.get(ctx.module, {}).get(
                        meth, [])
                    if len(owners) == 1:
                        return (self._class_method(ctx.module, owners[0],
                                                   meth), False)
            d = dotted(func)
            if d is not None and d.startswith(self.package + "."):
                mod, _, attr = d.rpartition(".")
                if mod in self.project.by_module:
                    return self._module_symbol(mod, attr), False
        return None, False

    def resolved_dotted(self, call: ast.Call, ctx: FunctionInfo
                        ) -> Optional[str]:
        """Dotted name with the leading import alias normalized to its
        real module path (``sleep`` -> ``time.sleep`` for
        ``from time import sleep``)."""
        d = dotted(call.func)
        if d is None:
            return None
        head, _, rest = d.partition(".")
        imp = self._import_target(ctx, head)
        if imp is None:
            return d
        kind, target = imp
        if kind == "module":
            return f"{target}.{rest}" if rest else target
        return f"{target}.{rest}" if rest else target

    # ------------------------------------------------- blocking analysis

    def direct_blocking_sites(self, info: FunctionInfo,
                              dotted_table: Dict[str, str],
                              methods_always: Dict[str, str],
                              methods_unbounded: Dict[str, str],
                              ) -> List[Tuple[int, str]]:
        """(line, label) for every blocking primitive called directly in
        this function (nested defs excluded — they run later)."""
        sites: List[Tuple[int, str]] = []
        for node in _walk_no_nested(info.node):
            if not isinstance(node, ast.Call):
                continue
            rd = self.resolved_dotted(node, info)
            if rd is not None and rd in dotted_table:
                sites.append((node.lineno, f"{rd} ({dotted_table[rd]})"))
                continue
            if isinstance(node.func, ast.Attribute):
                meth = node.func.attr
                if meth in methods_always:
                    sites.append(
                        (node.lineno,
                         f".{meth}() ({methods_always[meth]})"))
                elif meth in methods_unbounded and not node.args \
                        and not node.keywords:
                    sites.append(
                        (node.lineno,
                         f".{meth}() ({methods_unbounded[meth]})"))
        return sites

    def blocking_closure(self, dotted_table: Dict[str, str],
                         methods_always: Dict[str, str],
                         methods_unbounded: Dict[str, str],
                         ) -> Dict[str, List[str]]:
        """fqn -> shortest call chain (list of labels) ending at a
        blocking primitive, for every transitively-blocking function."""
        direct: Dict[str, List[Tuple[int, str]]] = {}
        edges: Dict[str, List[Tuple[str, int]]] = {}
        for fqn, info in self.functions.items():
            direct[fqn] = self.direct_blocking_sites(
                info, dotted_table, methods_always, methods_unbounded)
            outs: List[Tuple[str, int]] = []
            for node in _walk_no_nested(info.node):
                if isinstance(node, ast.Call):
                    callee, _ = self.resolve_call(node, info)
                    if callee is not None and callee in self.functions:
                        outs.append((callee, node.lineno))
            edges[fqn] = outs

        chains: Dict[str, List[str]] = {}
        for fqn, sites in direct.items():
            if sites:
                line, label = sites[0]
                chains[fqn] = [f"{_short(fqn)}:{line} -> {label}"]
        # BFS fixpoint: propagate the shortest chain to callers.
        changed = True
        while changed:
            changed = False
            for fqn, outs in edges.items():
                if fqn in chains:
                    continue
                for callee, line in outs:
                    if callee in chains:
                        chains[fqn] = (
                            [f"{_short(fqn)}:{line}"] + chains[callee])
                        changed = True
                        break
        return chains


def _walk_no_nested(fn_node: ast.AST):
    """Walk a function body without descending into nested defs/classes
    (those execute on their own schedule, not in this frame)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _short(fqn: str) -> str:
    return fqn.split(":", 1)[-1]
