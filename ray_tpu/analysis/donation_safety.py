"""Donation-aliasing family (#13): donated jit programs, statically.

Two real wrong-numbers bugs drive these rules. PR 14: a donated
executable reloaded from the persistent XLA disk cache segfaults or
returns wrong numbers (jaxlib 0.4.37), so the decode engine routes
every donated program's FIRST dispatch through ``_dispatch_fresh``,
which detaches the disk cache for that compile. PR 16: ``np.asarray``
over a jax dispatch result (or donated device state) returns a host
VIEW of the device buffer — the next donated dispatch clobbers it in
place, silently corrupting tokens already handed to clients; the
convention is ``np.array`` (an owning copy). Both were convention-only
across 60+ sites; these rules pin them:

**donation-unguarded-dispatch** — a program constructed with
``jit(..., donate_argnums=...)`` (recognized through wrapper calls
like ``_mesh_scoped``, via ``rules.DONATION_JIT_KWARGS``) and bound to
a ``self.`` attribute or local, dispatched WITHOUT flowing through a
guard named in ``rules.DONATED_DISPATCH_GUARDS`` (i.e. not inside an
argument of ``self._dispatch_fresh(key, lambda: ...)`` and not in the
guard's own body).

**donation-asarray-alias** — ``np.asarray(x)`` (import-resolved to
numpy — ``jnp.asarray`` is device-side and fine) inside a class that
owns donated programs, where ``x`` derives from donated device state:
a ``self.`` attribute that appears in a donated argument position or
is assigned from a dispatch result, or a local bound from a dispatch
result. Suggests ``np.array`` (copy).

**donation-read-after-donate** — a LOCAL passed in a donated argument
position and read again afterwards without an intervening rebind: the
dispatch invalidated the buffer, so the read observes freed/clobbered
device memory.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.analysis import rules
from ray_tpu.analysis.callgraph import CallGraph, FunctionInfo
from ray_tpu.analysis.core import Finding


def _walk_with_lambdas(fn_node: ast.AST):
    """Function-body walk that DOES descend into lambdas (a guarded
    dispatch lives inside ``lambda: self._prog(...)``) but not into
    nested defs/classes (separately indexed functions)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _donation_indices(call: ast.Call) -> Optional[Tuple[int, ...]]:
    for kw in call.keywords:
        if kw.arg in rules.DONATION_JIT_KWARGS:
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                idxs = tuple(e.value for e in v.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, int))
                return idxs or None
            return ()  # donating, indices unknown: guard still applies
    return None


def _find_donating_call(value: ast.AST) -> Optional[Tuple[int, ...]]:
    """Donated indices of the innermost donating jit construction in an
    assignment RHS (wrapper calls like _mesh_scoped included)."""
    for node in ast.walk(value):
        if isinstance(node, ast.Call):
            idxs = _donation_indices(node)
            if idxs is not None:
                return idxs
    return None


class _Index:
    """Per-project donation index: which self-attrs / locals bind
    donated programs, and which calls dispatch them."""

    def __init__(self, graph: CallGraph):
        self.graph = graph
        # (module, cls, attr) -> donated arg indices
        self.donated_attrs: Dict[Tuple[str, Optional[str], str],
                                 Tuple[int, ...]] = {}
        # fqn -> {local name -> donated arg indices}
        self.donated_locals: Dict[str, Dict[str, Tuple[int, ...]]] = {}
        graph.edges()
        for fqn, info in graph.functions.items():
            for node in _walk_with_lambdas(info.node):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1):
                    continue
                idxs = _find_donating_call(node.value)
                if idxs is None:
                    continue
                tgt = node.targets[0]
                if isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self" \
                        and info.cls is not None:
                    self.donated_attrs[
                        (info.module, info.cls, tgt.attr)] = idxs
                elif isinstance(tgt, ast.Name):
                    self.donated_locals.setdefault(fqn, {})[
                        tgt.id] = idxs
        self.owner_classes: Set[Tuple[str, str]] = {
            (mod, cls) for (mod, cls, _a) in self.donated_attrs}

    def dispatch_indices(self, call: ast.Call, info: FunctionInfo
                         ) -> Optional[Tuple[int, ...]]:
        """Donated arg indices when ``call`` dispatches a donated
        program (self-attr or local), else None."""
        func = call.func
        if isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Name) \
                and func.value.id == "self" and info.cls is not None:
            return self.donated_attrs.get(
                (info.module, info.cls, func.attr))
        if isinstance(func, ast.Name):
            return self.donated_locals.get(info.fqn, {}).get(func.id)
        return None


def _guarded_call_ids(info: FunctionInfo) -> Set[int]:
    """ids of every Call node inside an argument of a guard-wrapper
    call (the ``self._dispatch_fresh(key, lambda: ...)`` shape)."""
    out: Set[int] = set()
    for node in _walk_with_lambdas(info.node):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        tail = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None)
        if tail not in rules.DONATED_DISPATCH_GUARDS:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Call):
                    out.add(id(sub))
    return out


def _check_unguarded(index: _Index, findings: List[Finding]) -> None:
    for fqn, info in index.graph.functions.items():
        if (info.module, info.cls) not in index.owner_classes \
                and fqn not in index.donated_locals:
            continue
        if info.node.name in rules.DONATED_DISPATCH_GUARDS:
            continue    # the guard's own body IS the guarded path
        guarded = _guarded_call_ids(info)
        for node in _walk_with_lambdas(info.node):
            if not isinstance(node, ast.Call) or id(node) in guarded:
                continue
            if index.dispatch_indices(node, info) is None:
                continue
            prog = ast.unparse(node.func) if hasattr(ast, "unparse") \
                else "<donated program>"
            findings.append(Finding(
                rule=rules.DONATION_UNGUARDED,
                path=info.file.relpath, line=node.lineno,
                symbol=info.qualname,
                message=(f"donated program {prog} dispatched outside "
                         f"the fresh-compile guard "
                         f"({'/'.join(rules.DONATED_DISPATCH_GUARDS)}):"
                         f" its first dispatch may reload the donated "
                         f"executable from the persistent XLA cache "
                         f"(jaxlib 0.4.37: segfault or wrong numbers)"
                         f" — wrap it as self._dispatch_fresh(key, "
                         f"lambda: ...)")))


def _base_of(node: ast.AST) -> ast.AST:
    """Strip subscripts/slices: the object an expression views into."""
    while isinstance(node, ast.Subscript):
        node = node.value
    return node


def _donated_state(index: _Index, info: FunctionInfo
                   ) -> Tuple[Set[str], Set[str]]:
    """(self-attrs holding donated device state, locals bound from
    dispatch results) for one function: attrs fed into donated arg
    positions or assigned from dispatch results, and result locals of
    donated/guarded dispatch calls."""
    attrs: Set[str] = set()
    result_locals: Set[str] = set()

    def is_dispatch(call: ast.Call) -> bool:
        if index.dispatch_indices(call, info) is not None:
            return True
        func = call.func
        tail = func.attr if isinstance(func, ast.Attribute) else None
        return tail in rules.DONATED_DISPATCH_GUARDS

    for node in _walk_with_lambdas(info.node):
        if isinstance(node, ast.Call):
            idxs = index.dispatch_indices(node, info)
            if idxs:
                for i in idxs:
                    if i < len(node.args):
                        base = _base_of(node.args[i])
                        if isinstance(base, ast.Attribute) \
                                and isinstance(base.value, ast.Name) \
                                and base.value.id == "self":
                            attrs.add(base.attr)
        elif isinstance(node, ast.Assign) and isinstance(node.value,
                                                         ast.Call) \
                and is_dispatch(node.value):
            targets: List[ast.AST] = []
            for t in node.targets:
                targets += list(t.elts) if isinstance(
                    t, (ast.Tuple, ast.List)) else [t]
            for t in targets:
                if isinstance(t, ast.Name):
                    result_locals.add(t.id)
                elif isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    attrs.add(t.attr)
    return attrs, result_locals


def _check_asarray_alias(index: _Index,
                         findings: List[Finding]) -> None:
    graph = index.graph
    # donated state attrs are a CLASS property: any method's dispatch
    # teaches every other method's asarray check.
    cls_attrs: Dict[Tuple[str, str], Set[str]] = {}
    fn_locals: Dict[str, Set[str]] = {}
    for fqn, info in graph.functions.items():
        if (info.module, info.cls) not in index.owner_classes:
            continue
        attrs, result_locals = _donated_state(index, info)
        cls_attrs.setdefault((info.module, info.cls), set()).update(attrs)
        fn_locals[fqn] = result_locals
    for call, info in graph.calls_by_tail.get("asarray", ()):
        if (info.module, info.cls) not in index.owner_classes:
            continue
        rd = graph.resolved_dotted(call, info)
        if rd != "numpy.asarray" or not call.args:
            continue
        base = _base_of(call.args[0])
        hit: Optional[str] = None
        if isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name) \
                and base.value.id == "self" \
                and base.attr in cls_attrs.get(
                    (info.module, info.cls), ()):
            hit = f"self.{base.attr} (donated device state)"
        elif isinstance(base, ast.Name) \
                and base.id in fn_locals.get(info.fqn, ()):
            hit = f"{base.id} (a jax dispatch result)"
        if hit is None:
            continue
        findings.append(Finding(
            rule=rules.DONATION_ASARRAY_ALIAS,
            path=info.file.relpath, line=call.lineno,
            symbol=info.qualname,
            message=(f"np.asarray over {hit} returns a host VIEW of "
                     f"the device buffer — the next donated dispatch "
                     f"clobbers it in place (the PR 16 wrong-tokens "
                     f"bug); use np.array (an owning copy)")))


def _check_read_after_donate(index: _Index,
                             findings: List[Finding]) -> None:
    for fqn, info in index.graph.functions.items():
        if (info.module, info.cls) not in index.owner_classes \
                and fqn not in index.donated_locals:
            continue
        dispatches: List[Tuple[ast.Call, Tuple[int, ...]]] = []
        for node in _walk_with_lambdas(info.node):
            if isinstance(node, ast.Call):
                idxs = index.dispatch_indices(node, info)
                if idxs:
                    dispatches.append((node, idxs))
        if not dispatches:
            continue
        stores: Dict[str, List[int]] = {}
        loads: Dict[str, List[ast.Name]] = {}
        for node in _walk_with_lambdas(info.node):
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    loads.setdefault(node.id, []).append(node)
                else:
                    stores.setdefault(node.id, []).append(node.lineno)
        for call, idxs in dispatches:
            for i in idxs:
                if i >= len(call.args) \
                        or not isinstance(call.args[i], ast.Name):
                    continue
                name = call.args[i].id
                for load in loads.get(name, ()):
                    if load.lineno <= call.lineno:
                        continue
                    # >= call line, not >: the rebind target of
                    # ``x, c = f(c)`` shares the dispatch's line and IS
                    # the safe idiom (the result replaces the donated
                    # buffer before any later read).
                    if any(call.lineno <= s <= load.lineno
                           for s in stores.get(name, ())):
                        continue
                    findings.append(Finding(
                        rule=rules.DONATION_READ_AFTER_DONATE,
                        path=info.file.relpath, line=load.lineno,
                        symbol=info.qualname,
                        message=(f"{name!r} is read after being passed "
                                 f"in donated argument position {i} of "
                                 f"a dispatch at line {call.lineno}: "
                                 f"donation invalidated the buffer, so "
                                 f"this read observes freed/clobbered "
                                 f"device memory")))
                    break   # one finding per (dispatch, name)


def check(graph: CallGraph,
          emit_files: Optional[set] = None) -> List[Finding]:
    index = _Index(graph)
    findings: List[Finding] = []
    _check_unguarded(index, findings)
    _check_asarray_alias(index, findings)
    _check_read_after_donate(index, findings)
    if emit_files is not None:
        findings = [f for f in findings if f.path in emit_files]
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
