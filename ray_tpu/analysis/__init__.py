"""graftlint: AST-based concurrency & trace-safety analysis for ray_tpu.

Four checkers fitted to this codebase's real failure modes (each rule is
documented in docs/ANALYSIS.md):

=====================  ==================================================
rule                   catches
=====================  ==================================================
reactor-blocking-call  blocking calls reachable from core/rpc.py selector
                       callbacks (the PR 1 bug class)
trace-host-sync        .item()/np.asarray/device_get inside @jax.jit
trace-python-branch    Python if/while on traced values inside jit
trace-retrace-hazard   traced values in shape positions, set iteration
lock-order-cycle       lock-acquisition ordering cycles / self-deadlocks
lock-held-blocking     RPC sends, connects, sleeps under a held lock
swallowed-exception    ``except Exception: pass`` (the PR 3 bug class)
missing-finally-release  acquire/release in one function w/o finally
=====================  ==================================================

Run it: ``python -m ray_tpu.analysis [--strict] [--format json]``, or
``make lint``. Suppress a deliberate site with
``# graftlint: disable=<rule>`` (same line or the line above); defer a
triaged finding via ``analysis/baseline.json``
(``--write-baseline``, then fill in the ``reason``). The tier-1 gate
(tests/test_analysis.py) fails on any unbaselined finding.

Pure stdlib ``ast`` — no jax import, no third-party deps; a full-repo
run takes a few seconds (budgeted < 10 s, see BENCH_NOTES.md).
"""

from __future__ import annotations

import os
import time
from typing import Dict, Iterable, List, Optional, Tuple

from ray_tpu.analysis import rules
from ray_tpu.analysis.core import (Baseline, Finding, Project,
                                   assign_fingerprints)

__all__ = ["run_analysis", "Finding", "Baseline", "Project",
           "DEFAULT_BASELINE", "repo_root"]

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__),
                                "baseline.json")


def repo_root() -> str:
    """The directory containing the ``ray_tpu`` package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def run_analysis(root: Optional[str] = None,
                 select: Optional[Iterable[str]] = None,
                 paths: Optional[Iterable[str]] = None,
                 ) -> Tuple[List[Finding], Dict[str, float]]:
    """Run every (selected) checker over the package.

    Returns (findings, stats). Findings are pragma-filtered and carry
    fingerprints, but are NOT baseline-filtered — callers split against
    a Baseline themselves. ``paths`` restricts reported findings to
    files whose relpath starts with one of the given prefixes (the whole
    package is still parsed: the call graph needs it).
    """
    from ray_tpu.analysis import (lifecycle_hygiene, lock_discipline,
                                  reactor_safety, trace_safety)
    from ray_tpu.analysis.callgraph import CallGraph

    t0 = time.perf_counter()
    root = root or repo_root()
    project = Project.load(root)
    t_parse = time.perf_counter() - t0

    selected = set(select) if select else set(rules.ALL_RULES)
    findings: List[Finding] = []
    per_rule: Dict[str, float] = {}

    def timed(label: str, fn, *args) -> List[Finding]:
        t = time.perf_counter()
        out = fn(*args)
        per_rule[label] = time.perf_counter() - t
        return out

    graph = None
    need_graph = selected & {rules.REACTOR_BLOCKING, rules.TRACE_HOST_SYNC,
                             rules.TRACE_PY_BRANCH, rules.TRACE_RETRACE,
                             rules.LOCK_ORDER_CYCLE,
                             rules.LOCK_HELD_BLOCKING}
    if need_graph:
        t = time.perf_counter()
        graph = CallGraph(project)
        per_rule["callgraph"] = time.perf_counter() - t
    if rules.REACTOR_BLOCKING in selected:
        findings += timed("reactor-safety", reactor_safety.check, graph)
    if selected & {rules.TRACE_HOST_SYNC, rules.TRACE_PY_BRANCH,
                   rules.TRACE_RETRACE}:
        findings += timed("trace-safety", trace_safety.check, graph)
    if selected & {rules.LOCK_ORDER_CYCLE, rules.LOCK_HELD_BLOCKING}:
        findings += timed("lock-discipline", lock_discipline.check, graph)
    if selected & {rules.SWALLOWED_EXCEPTION, rules.MISSING_FINALLY}:
        findings += timed("lifecycle-hygiene",
                          lifecycle_hygiene.check_project, project)

    findings = [f for f in findings if f.rule in selected]
    if paths:
        prefixes = tuple(p.rstrip("/") for p in paths)
        findings = [f for f in findings
                    if any(f.path == p or f.path.startswith(p + "/")
                           or f.path.startswith(p)
                           for p in prefixes)]
    # pragma suppression
    by_rel = {f.relpath: f for f in project.files}
    findings = [f for f in findings
                if not (f.path in by_rel
                        and by_rel[f.path].suppressed(f.rule, f.line))]
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    assign_fingerprints(findings)

    stats = {"files": float(len(project.files)),
             "parse_s": t_parse,
             "total_s": time.perf_counter() - t0}
    stats.update({f"{k}_s": v for k, v in per_rule.items()})
    return findings, stats
