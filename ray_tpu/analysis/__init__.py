"""graftlint: AST-based concurrency & trace-safety analysis for ray_tpu.

Fourteen checker families fitted to this codebase's real failure modes
(each rule is documented in docs/ANALYSIS.md):

=====================  ==================================================
rule                   catches
=====================  ==================================================
reactor-blocking-call  blocking calls reachable from core/rpc.py selector
                       callbacks (the PR 1 bug class)
trace-host-sync        .item()/np.asarray/device_get inside @jax.jit
trace-python-branch    Python if/while on traced values inside jit
trace-retrace-hazard   traced values in shape positions, set iteration
lock-order-cycle       lock-acquisition ordering cycles / self-deadlocks
lock-held-blocking     RPC sends, connects, sleeps under a held lock
swallowed-exception    ``except Exception: pass`` (the PR 3 bug class)
missing-finally-release  lock acquire/release in one function w/o finally
unguarded-field-access guarded-by inference: a field locked at a majority
                       of sites, accessed lock-free from 2+-thread code
resource-leak-path     a path (incl. exception edges) exiting a scope
                       with a socket/registration/slot/pin/topology
                       lease still live
rpc-unknown-method     .call("x")/.notify("x") with no registered handler
rpc-arity-mismatch     call arg shape no registration of the name accepts
rpc-dead-endpoint      handler registered but never called in-package
sharding-partitioned-contraction  a DECODE_RULES entry partitioning a
                       contraction dim at an einsum/matmul site (split
                       reduction = bit-exactness broken), statically
sharding-missing-anchor  a row-parallel reduction (wo / w_down) whose
                       activation operand has no ``constrain`` anchor
sharding-unpinned-mesh-call  jit/device_put inside a mesh scope without
                       in_shardings/out_shardings
sharding-unscoped-trace  a sharded program (reaches ``constrain``)
                       jitted with sharding kwargs outside axis_rules
rpc-stub-drift         core/rpc_stubs.py stale vs the handler index
                       (regenerate with ``--gen-stubs``)
fence-result-ignored   a fenced write (kv_put_fenced / epoch publish /
                       mh_group_put / pipe_step_complete) whose stale-
                       epoch verdict is discarded, incl. through
                       fence-carrier return chains
unfenced-mutation-in-fenced-class  raw kv_put / epoch-less publish
                       inside a class whose state is epoch-fenced
epoch-compare-direction  a stored-clock comparison whose direction
                       contradicts the table (equal-ok vs strict)
epoch-not-threaded     fenced publish whose dict payload lacks the
                       epoch/version key subscribers fence against
donation-unguarded-dispatch  a donate_argnums program dispatched
                       outside _dispatch_fresh (PR 14 reload footgun)
donation-asarray-alias np.asarray over donated device state / dispatch
                       results (PR 16 host-view clobber; use np.array)
donation-read-after-donate  a local read again after being passed in a
                       donated argument position
unbounded-blocking-call  Event.wait()/Queue.get()/join()/result()/socket
                       recv with no finite bound, reachable from any
                       thread entry point (RPC handlers, Thread/Timer
                       targets, executor submits — the silent hang)
rpc-call-no-timeout    control-plane .call("x")/stub sites without
                       timeout= (timeout=None parks forever)
deadline-not-propagated  a timeout_s/deadline parameter handed raw to
                       2+ blocking calls (or dropped) instead of a
                       util.deadline.Deadline remaining-time budget
retry-unbounded        while-True dial/RPC loops with no backoff,
                       attempt bound, or deadline (reconnect storm)
timeout-knob-dead      a *_timeout_s config knob no code ever reads
stale-pragma           a ``# graftlint:`` pragma suppressing nothing
                       (computed centrally on full runs)
=====================  ==================================================

Run it: ``python -m ray_tpu.analysis [--strict] [--format json]
[--jobs N] [--diff REF] [--gen-stubs]``, or ``make lint`` /
``make lint-diff``.
Suppress a deliberate site with ``# graftlint: disable=<rule>`` (same
line or the line above); defer a triaged finding via
``analysis/baseline.json`` (``--write-baseline``, then fill in the
``reason``). The tier-1 gate (tests/test_analysis.py) fails on any
unbaselined finding.

Pure stdlib ``ast`` — no jax import, no third-party deps; a full-repo
run takes a few seconds (budgeted < 10 s, see BENCH_NOTES.md).
"""

from __future__ import annotations

import os
import time
from typing import Dict, Iterable, List, Optional, Tuple

from ray_tpu.analysis import rules
from ray_tpu.analysis.core import (Baseline, Finding, Project,
                                   assign_fingerprints)

__all__ = ["run_analysis", "Finding", "Baseline", "Project",
           "DEFAULT_BASELINE", "repo_root"]

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__),
                                "baseline.json")

# Set in the parent before forking --jobs workers; children inherit the
# parsed project/graph via copy-on-write and ship only findings back.
_FORK_CTX: Dict[str, object] = {}


def repo_root() -> str:
    """The directory containing the ``ray_tpu`` package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _family_checks():
    """family name -> (needs_graph, check callable). Every check takes
    (project_or_graph, emit_files=None): whole-program indexes are
    always built, but per-file emission work is skipped for files
    outside ``emit_files`` (the --diff fast path)."""
    from ray_tpu.analysis import (autopilot_lint, deadline_safety,
                                  donation_safety, fence_safety,
                                  guarded_by, lifecycle_hygiene,
                                  lifetime, lock_discipline,
                                  metrics_lint, reactor_safety,
                                  rpc_contract, sharding_safety,
                                  stubgen, trace_safety)

    return {
        "reactor-safety": (True, reactor_safety.check),
        "trace-safety": (True, trace_safety.check),
        "lock-discipline": (True, lock_discipline.check),
        "lifecycle-hygiene": (False, lifecycle_hygiene.check_project),
        "guarded-by": (True, guarded_by.check),
        "lifetime": (True, lifetime.check),
        "rpc-contract": (True, rpc_contract.check),
        "sharding-safety": (True, sharding_safety.check),
        "rpc-stubs": (True, stubgen.check),
        "metrics": (False, metrics_lint.check_project),
        "autopilot": (False, autopilot_lint.check_project),
        "fence-safety": (True, fence_safety.check),
        "donation-aliasing": (True, donation_safety.check),
        "deadline-safety": (True, deadline_safety.check),
    }


def _stale_pragma_findings(project: Project,
                           raw: List[Finding]) -> List[Finding]:
    """One finding per ``# graftlint: disable=...`` comment that no
    longer suppresses anything: none of the rules it names has a raw
    finding on a line the pragma covers (its own line, plus the next
    code line for standalone comments). A pragma naming an unknown
    rule is stale by definition — it can never fire."""
    by_path: Dict[str, Dict[int, set]] = {}
    for f in raw:
        by_path.setdefault(f.path, {}).setdefault(
            f.line, set()).add(f.rule)
    known = set(rules.ALL_RULES)
    out: List[Finding] = []
    for sf in project.files:
        lines = by_path.get(sf.relpath, {})
        for row, names, covered in sf.pragma_sites:
            hit = False
            for cov in covered:
                found = lines.get(cov, set())
                if any((n == "all" and found)
                       or (n in known and n in found)
                       for n in names):
                    hit = True
                    break
            if hit:
                continue
            unknown = sorted(n for n in names
                             if n != "all" and n not in known)
            why = (f"names unknown rule(s) {', '.join(unknown)}"
                   if unknown else "suppresses no live finding")
            out.append(Finding(
                rule=rules.STALE_PRAGMA, path=sf.relpath, line=row,
                symbol="",
                message=f"pragma disable={','.join(sorted(names))} "
                        f"{why}; delete it (stale suppressions hide "
                        f"future regressions)"))
    return out


def _run_family(name: str) -> Tuple[str, List[Finding], float]:
    needs_graph, fn = _family_checks()[name]
    t = time.perf_counter()
    arg = _FORK_CTX["graph"] if needs_graph else _FORK_CTX["project"]
    out = fn(arg, emit_files=_FORK_CTX.get("emit_files"))
    return name, out, time.perf_counter() - t


def run_analysis(root: Optional[str] = None,
                 select: Optional[Iterable[str]] = None,
                 paths: Optional[Iterable[str]] = None,
                 jobs: int = 1,
                 emit_files: Optional[Iterable[str]] = None,
                 ) -> Tuple[List[Finding], Dict[str, float]]:
    """Run every (selected) checker over the package.

    Returns (findings, stats). Findings are pragma-filtered and carry
    fingerprints, but are NOT baseline-filtered — callers split against
    a Baseline themselves. ``paths`` restricts reported findings to
    files whose relpath starts with one of the given prefixes (the whole
    package is still parsed: the call graph needs it). ``jobs`` > 1
    forks that many workers and runs checker families in parallel
    (fork shares the parsed ASTs copy-on-write; falls back to serial
    where fork is unavailable). ``emit_files`` (exact relpaths — the
    --diff fast path) additionally skips per-file emission WORK inside
    the checkers; whole-program indexes still cover the package, so
    cross-file findings in the listed files stay sound.
    """
    from ray_tpu.analysis.callgraph import CallGraph

    t0 = time.perf_counter()
    root = root or repo_root()
    project = Project.load(root)
    t_parse = time.perf_counter() - t0

    selected = set(select) if select else set(rules.ALL_RULES)
    families = [name for name, fam_rules in rules.FAMILIES.items()
                if selected & set(fam_rules)]
    findings: List[Finding] = []
    per_rule: Dict[str, float] = {}

    graph = None
    need_graph = any(_family_checks()[name][0] for name in families)
    if need_graph:
        t = time.perf_counter()
        graph = CallGraph(project)
        graph.edges()  # precompute once; forked workers share it COW
        per_rule["callgraph"] = time.perf_counter() - t

    _FORK_CTX["project"] = project
    _FORK_CTX["graph"] = graph
    _FORK_CTX["emit_files"] = set(emit_files) if emit_files else None
    try:
        if jobs > 1 and len(families) > 1 and hasattr(os, "fork"):
            import multiprocessing

            ctx = multiprocessing.get_context("fork")
            with ctx.Pool(min(jobs, len(families))) as pool:
                results = pool.map(_run_family, families)
        else:
            results = [_run_family(name) for name in families]
    finally:
        _FORK_CTX.clear()
    for name, fam_findings, dt in results:
        findings += fam_findings
        per_rule[name] = dt

    findings = [f for f in findings if f.rule in selected]
    # Stale-pragma hygiene, computed centrally on FULL runs only (a
    # family-selected or path/diff-sliced run does not see every rule's
    # raw findings, so pragma liveness would read falsely stale there).
    # Uses pre-suppression findings: a pragma is live exactly when it
    # suppresses >= 1 finding some family would otherwise emit.
    stale: List[Finding] = []
    if select is None and paths is None and emit_files is None:
        stale = _stale_pragma_findings(project, findings)
    # per-rule counts BEFORE pragma suppression (the --stats-json
    # trajectory tracks total analyzer debt, suppressed or not)
    raw_counts: Dict[str, int] = {}
    for f in findings + stale:
        raw_counts[f.rule] = raw_counts.get(f.rule, 0) + 1
    if paths:
        prefixes = tuple(p.rstrip("/") for p in paths)
        findings = [f for f in findings
                    if any(f.path == p or f.path.startswith(p + "/")
                           or f.path.startswith(p)
                           for p in prefixes)]
    # pragma suppression (stale-pragma findings join afterwards: a
    # pragma must never be able to suppress its own staleness verdict)
    by_rel = {f.relpath: f for f in project.files}
    findings = [f for f in findings
                if not (f.path in by_rel
                        and by_rel[f.path].suppressed(f.rule, f.line))]
    findings += stale
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    assign_fingerprints(findings)

    stats = {"files": float(len(project.files)),
             "parse_s": t_parse,
             "total_s": time.perf_counter() - t0}
    stats.update({f"{k}_s": v for k, v in per_rule.items()})
    for rule, n in sorted(raw_counts.items()):
        stats[f"raw_{rule}"] = float(n)
    reported: Dict[str, int] = {}
    for f in findings:
        reported[f.rule] = reported.get(f.rule, 0) + 1
    for rule, n in sorted(reported.items()):
        stats[f"reported_{rule}"] = float(n)
    return findings, stats
