"""graftlint core: findings, pragma suppression, project loading, baseline.

The analyzer is pure-AST (stdlib ``ast`` + ``tokenize`` only): it never
imports the modules it checks, so it runs in milliseconds, needs no jax,
and is safe to run on code that would crash on import. Everything here is
shared by the four checkers (reactor-safety, trace-safety, lock-discipline,
lifecycle-hygiene — see the sibling modules).

Suppression model, outermost to innermost:

* ``analysis/baseline.json`` — triaged-but-deferred findings, matched by a
  line-number-independent fingerprint (rule | path | enclosing symbol |
  occurrence index within that symbol) so unrelated edits don't churn it.
* ``# graftlint: disable=<rule>[,<rule>...]`` pragma comments — on the
  flagged line, or standing alone on the line above a statement. ``all``
  disables every rule for that line. Pragmas are for *deliberate* code
  ("this lock exists to serialize this blocking send"); the baseline is
  for debt.
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

PRAGMA_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\- ]+)")


@dataclass
class Finding:
    rule: str
    path: str        # repo-relative posix path
    line: int
    symbol: str      # enclosing function qualname, or "<module>"
    message: str
    fingerprint: str = ""

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.symbol}: " \
               f"{self.message}"

    def to_json(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "symbol": self.symbol, "message": self.message,
                "fingerprint": self.fingerprint}


class SourceFile:
    """One parsed module: AST, dotted module name, pragma map."""

    def __init__(self, abspath: str, relpath: str, text: str):
        self.abspath = abspath
        self.relpath = relpath.replace("\\", "/")
        self.text = text
        self.tree = ast.parse(text, filename=relpath)
        mod = self.relpath[:-3] if self.relpath.endswith(".py") else \
            self.relpath
        if mod.endswith("/__init__"):
            mod = mod[: -len("/__init__")]
        self.module = mod.replace("/", ".")
        # line -> set of disabled rule names ("all" disables everything)
        self.pragmas, self.pragma_sites = _extract_pragmas(text)

    def suppressed(self, rule: str, line: int) -> bool:
        rules = self.pragmas.get(line)
        return bool(rules) and ("all" in rules or rule in rules)


def _extract_pragmas(text: str) -> Tuple[Dict[int, Set[str]],
                                         List[Tuple[int, frozenset,
                                                    Tuple[int, ...]]]]:
    """-> (line -> disabled rules, pragma sites). A site is ONE pragma
    comment: (its own row, the rules it names, the code rows it covers
    — its row plus, for standalone comments, the next code row). Sites
    feed the stale-pragma liveness check: a pragma none of whose
    covered rows carries a live finding of a named rule is dead weight
    and fails strict."""
    out: Dict[int, Set[str]] = {}
    sites: List[Tuple[int, frozenset, Tuple[int, ...]]] = []
    standalone: List[Tuple[int, Set[str]]] = []
    code_rows: Set[int] = set()
    # Fast path: tokenizing every file dominates parse time, and most
    # files carry no pragma at all — a substring probe is enough to skip
    # them (a false hit here just pays the tokenize).
    if "graftlint" not in text:
        return out, sites
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(text).readline))
    except tokenize.TokenError:
        return out, sites
    for tok in tokens:
        if tok.type == tokenize.COMMENT:
            m = PRAGMA_RE.search(tok.string)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            row = tok.start[0]
            out.setdefault(row, set()).update(rules)
            if tok.line[: tok.start[1]].strip() == "":
                standalone.append((row, rules))
            else:
                sites.append((row, frozenset(rules), (row,)))
        elif tok.type not in (tokenize.NL, tokenize.NEWLINE,
                              tokenize.INDENT, tokenize.DEDENT,
                              tokenize.ENDMARKER):
            code_rows.add(tok.start[0])
    # A pragma on its own line also covers the next code line, so long
    # statements don't need the comment crammed onto them.
    for row, rules in standalone:
        nxt = min((r for r in code_rows if r > row), default=None)
        if nxt is not None:
            out.setdefault(nxt, set()).update(rules)
        sites.append((row, frozenset(rules),
                      (row,) if nxt is None else (row, nxt)))
    return out, sites


class Project:
    """All package sources under a root, parsed once and shared."""

    def __init__(self, root: str, files: List[SourceFile]):
        self.root = root
        self.files = sorted(files, key=lambda f: f.relpath)
        self.by_module: Dict[str, SourceFile] = {
            f.module: f for f in self.files}

    @classmethod
    def load(cls, root: str, package: str = "ray_tpu",
             exclude: Iterable[str] = ()) -> "Project":
        import os

        files: List[SourceFile] = []
        pkg_dir = os.path.join(root, package)
        excl = tuple(exclude)
        for dirpath, dirnames, filenames in os.walk(pkg_dir):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and not d.startswith(".")]
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                abspath = os.path.join(dirpath, name)
                relpath = os.path.relpath(abspath, root)
                rp = relpath.replace("\\", "/")
                if any(rp.startswith(e) for e in excl):
                    continue
                try:
                    with open(abspath, "r", encoding="utf-8") as fh:
                        text = fh.read()
                    files.append(SourceFile(abspath, relpath, text))
                except (SyntaxError, UnicodeDecodeError, OSError):
                    # Unparseable files are a job for the test suite, not
                    # the linter; skip rather than crash the whole run.
                    continue
        return cls(root, files)


def qualname_of(stack: List[ast.AST]) -> str:
    """Dotted qualname for the innermost function in a nesting stack."""
    parts = [n.name for n in stack
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef))]
    return ".".join(parts) if parts else "<module>"


def assign_fingerprints(findings: List[Finding]) -> None:
    """Stable IDs: occurrence index within (rule, path, symbol), so line
    drift from unrelated edits does not invalidate baseline entries."""
    groups: Dict[Tuple[str, str, str], List[Finding]] = {}
    for f in findings:
        groups.setdefault((f.rule, f.path, f.symbol), []).append(f)
    for (rule, path, symbol), group in groups.items():
        group.sort(key=lambda f: f.line)
        for occ, f in enumerate(group):
            raw = f"{rule}|{path}|{symbol}|{occ}"
            f.fingerprint = hashlib.sha1(raw.encode()).hexdigest()[:16]


@dataclass
class Baseline:
    entries: Dict[str, Dict[str, object]] = field(default_factory=dict)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        try:
            with open(path, "r", encoding="utf-8") as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            return cls()
        entries = {e["fingerprint"]: e
                   for e in data.get("entries", [])
                   if isinstance(e, dict) and "fingerprint" in e}
        return cls(entries)

    def split(self, findings: List[Finding]
              ) -> Tuple[List[Finding], List[Finding], List[Dict]]:
        """-> (new, baselined, stale-entries)."""
        new: List[Finding] = []
        hit: Set[str] = set()
        baselined: List[Finding] = []
        for f in findings:
            if f.fingerprint in self.entries:
                baselined.append(f)
                hit.add(f.fingerprint)
            else:
                new.append(f)
        stale = [e for fp, e in self.entries.items() if fp not in hit]
        return new, baselined, stale

    def write(self, path: str, findings: List[Finding],
              default_reason: str = "TODO: triage") -> None:
        """Merge current findings into the baseline, keeping the reasons
        of entries that still match."""
        merged = []
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
            old = self.entries.get(f.fingerprint, {})
            merged.append({
                "fingerprint": f.fingerprint, "rule": f.rule,
                "path": f.path, "line": f.line, "symbol": f.symbol,
                "message": f.message,
                "reason": old.get("reason", default_reason)})
        with open(path, "w", encoding="utf-8") as fh:
            json.dump({"version": 1, "entries": merged}, fh, indent=1)
            fh.write("\n")
