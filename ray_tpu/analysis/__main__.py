"""graftlint CLI: ``python -m ray_tpu.analysis``.

Exit codes: 0 = clean (or non-strict), 1 = unbaselined findings with
``--strict``, 2 = bad usage. ``--write-baseline`` snapshots current
findings into analysis/baseline.json (reasons of surviving entries are
preserved; fill in new ones by hand — shipping ``TODO: triage`` reasons
is a review smell, see docs/ANALYSIS.md).
"""

from __future__ import annotations

import argparse
import json
import sys

from ray_tpu.analysis import (DEFAULT_BASELINE, Baseline, repo_root,
                              run_analysis)
from ray_tpu.analysis import rules as _rules


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.analysis",
        description="graftlint: AST concurrency & trace-safety analysis")
    parser.add_argument("paths", nargs="*",
                        help="restrict findings to these repo-relative "
                             "path prefixes (default: whole package)")
    parser.add_argument("--root", default=None,
                        help="repo root (default: auto-detected)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule subset")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--strict", action="store_true",
                        help="exit 1 on any unbaselined finding")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE)
    parser.add_argument("--no-baseline", action="store_true",
                        help="report everything, ignoring the baseline")
    parser.add_argument("--write-baseline", action="store_true",
                        help="merge current findings into the baseline")
    parser.add_argument("--stats", action="store_true",
                        help="print per-checker timings")
    args = parser.parse_args(argv)

    if args.list_rules:
        for r in _rules.ALL_RULES:
            print(r)
        return 0

    select = None
    if args.rules:
        select = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in select if r not in _rules.ALL_RULES]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2

    findings, stats = run_analysis(root=args.root or repo_root(),
                                   select=select, paths=args.paths)
    baseline = Baseline() if args.no_baseline \
        else Baseline.load(args.baseline)
    new, baselined, stale = baseline.split(findings)

    if args.write_baseline:
        baseline.write(args.baseline, findings,
                       default_reason="TODO: triage")
        print(f"wrote {len(findings)} entries to {args.baseline}")
        return 0

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in new],
            "baselined": len(baselined),
            "stale_baseline_entries": len(stale),
            "stats": stats}, indent=1))
    else:
        for f in new:
            print(f.render())
        counts = {}
        for f in new:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        summary = ", ".join(f"{r}: {n}" for r, n in sorted(counts.items()))
        print(f"graftlint: {len(new)} finding(s)"
              + (f" [{summary}]" if summary else "")
              + f", {len(baselined)} baselined, "
              f"{len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'}, "
              f"{int(stats['files'])} files in {stats['total_s']:.2f}s")
        if stale and args.strict:
            for e in stale:
                print(f"  stale baseline: {e.get('path')}:"
                      f"{e.get('line')} [{e.get('rule')}] "
                      f"{e.get('symbol')}")
        if args.stats:
            for k, v in stats.items():
                if k.endswith("_s"):
                    print(f"  {k[:-2]:>20}: {v * 1e3:7.1f} ms")

    if args.strict and (new or stale):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
