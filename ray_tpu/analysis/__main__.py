"""graftlint CLI: ``python -m ray_tpu.analysis``.

Exit codes: 0 = clean (or non-strict), 1 = unbaselined findings with
``--strict``, 2 = bad usage. ``--write-baseline`` snapshots current
findings into analysis/baseline.json (reasons of surviving entries are
preserved; fill in new ones by hand — shipping ``TODO: triage`` reasons
is a review smell, see docs/ANALYSIS.md).
"""

from __future__ import annotations

import argparse
import json
import sys

from ray_tpu.analysis import (DEFAULT_BASELINE, Baseline, repo_root,
                              run_analysis)
from ray_tpu.analysis import rules as _rules


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.analysis",
        description="graftlint: AST concurrency & trace-safety analysis")
    parser.add_argument("paths", nargs="*",
                        help="restrict findings to these repo-relative "
                             "path prefixes (default: whole package)")
    parser.add_argument("--root", default=None,
                        help="repo root (default: auto-detected)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule subset")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--strict", action="store_true",
                        help="exit 1 on any unbaselined finding")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE)
    parser.add_argument("--no-baseline", action="store_true",
                        help="report everything, ignoring the baseline")
    parser.add_argument("--write-baseline", action="store_true",
                        help="merge current findings into the baseline")
    parser.add_argument("--stats", action="store_true",
                        help="print per-checker timings")
    parser.add_argument("--stats-json", default=None, metavar="PATH",
                        help="write per-rule timing/finding-count JSON "
                             "artifact to PATH")
    parser.add_argument("--jobs", type=int, default=0, metavar="N",
                        help="fork N workers to run checker families in "
                             "parallel (0 = auto: one per family when "
                             "the platform supports fork)")
    parser.add_argument("--diff", default=None, metavar="REF",
                        help="only report findings in package files "
                             "changed vs this git ref (plus untracked "
                             "files); implies --jobs auto")
    parser.add_argument("--gen-stubs", nargs="?", const="", default=None,
                        metavar="PATH",
                        help="regenerate the typed RPC client stubs "
                             "from the handler index (default: "
                             "ray_tpu/core/rpc_stubs.py) and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for r in _rules.ALL_RULES:
            print(r)
        return 0

    select = None
    if args.rules:
        select = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in select if r not in _rules.ALL_RULES]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2

    root = args.root or repo_root()
    if args.gen_stubs is not None:
        return _gen_stubs(root, args.gen_stubs)
    paths = list(args.paths)
    emit_files = None
    if args.diff is not None:
        changed = _changed_package_files(root, args.diff)
        if changed is None:
            print(f"--diff: git diff against {args.diff!r} failed",
                  file=sys.stderr)
            return 2
        if not changed:
            print("graftlint: no package files changed "
                  f"vs {args.diff}; nothing to check")
            return 0
        emit_files = changed

    jobs = args.jobs
    if jobs <= 0:
        # auto: fork-parallel families when the box has the cores for
        # it; a single-core box runs serial (fork would only add
        # scheduler churn). --jobs 1 forces serial explicitly.
        import os as _os
        cores = _os.cpu_count() or 1
        jobs = min(len(_rules.FAMILIES), cores) \
            if hasattr(_os, "fork") else 1

    findings, stats = run_analysis(root=root, select=select,
                                   paths=paths or None, jobs=jobs,
                                   emit_files=emit_files)
    baseline = Baseline() if args.no_baseline \
        else Baseline.load(args.baseline)
    new, baselined, stale = baseline.split(findings)
    if args.diff is not None:
        # a diff run sees only a slice of the repo: absent findings say
        # nothing about baseline entries outside the slice
        stale = []

    if args.stats_json:
        _write_stats_json(args.stats_json, stats, new, baselined)

    if args.write_baseline:
        baseline.write(args.baseline, findings,
                       default_reason="TODO: triage")
        print(f"wrote {len(findings)} entries to {args.baseline}")
        return 0

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in new],
            "baselined": len(baselined),
            "stale_baseline_entries": len(stale),
            "stats": stats}, indent=1))
    else:
        for f in new:
            print(f.render())
        counts = {}
        for f in new:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        summary = ", ".join(f"{r}: {n}" for r, n in sorted(counts.items()))
        print(f"graftlint: {len(new)} finding(s)"
              + (f" [{summary}]" if summary else "")
              + f", {len(baselined)} baselined, "
              f"{len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'}, "
              f"{int(stats['files'])} files in {stats['total_s']:.2f}s")
        if stale and args.strict:
            for e in stale:
                print(f"  stale baseline: {e.get('path')}:"
                      f"{e.get('line')} [{e.get('rule')}] "
                      f"{e.get('symbol')}")
        if args.stats:
            for k, v in stats.items():
                if k.endswith("_s"):
                    print(f"  {k[:-2]:>20}: {v * 1e3:7.1f} ms")

    if args.strict and (new or stale):
        return 1
    return 0


def _gen_stubs(root, out_path):
    """Regenerate ray_tpu/core/rpc_stubs.py from the handler index."""
    import os

    from ray_tpu.analysis import Project
    from ray_tpu.analysis import rules as r
    from ray_tpu.analysis import stubgen
    from ray_tpu.analysis.callgraph import CallGraph

    project = Project.load(root)
    graph = CallGraph(project)
    src = stubgen.generate(graph)
    path = out_path or os.path.join(root, r.RPC_STUBS_PATH)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(src)
    print(f"wrote {path} ({len(src.splitlines())} lines)")
    return 0


def _changed_package_files(root, ref):
    """Package .py files changed vs ``ref`` (plus untracked), or None on
    git failure."""
    import subprocess

    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", ref, "--"],
            capture_output=True, text=True, cwd=root, timeout=30)
        if diff.returncode != 0:
            return None
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            capture_output=True, text=True, cwd=root, timeout=30)
    except (OSError, subprocess.SubprocessError):
        return None
    names = diff.stdout.splitlines()
    if untracked.returncode == 0:
        names += untracked.stdout.splitlines()
    return sorted({n for n in names
                   if n.endswith(".py") and n.startswith("ray_tpu/")})


def _write_stats_json(path, stats, new, baselined):
    """Per-rule JSON artifact: timings, raw/reported finding counts,
    and the number of pragma-suppressed sites per rule (raw - reported)
    — the analyzer-debt trajectory tracked in BENCH_NOTES.md."""
    from ray_tpu.analysis import rules as r

    per_rule = {}
    for rule in r.ALL_RULES:
        raw = int(stats.get(f"raw_{rule}", 0.0))
        reported = int(stats.get(f"reported_{rule}", 0.0))
        per_rule[rule] = {
            "raw": raw,
            "pragma_suppressed": raw - reported,
            "reported_unbaselined": sum(1 for f in new if f.rule == rule),
            "baselined": sum(1 for f in baselined if f.rule == rule),
        }
    artifact = {
        "files": int(stats.get("files", 0.0)),
        "total_s": round(stats.get("total_s", 0.0), 3),
        "timings_s": {k[:-2]: round(v, 4) for k, v in stats.items()
                      if k.endswith("_s")},
        "rules": per_rule,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(artifact, fh, indent=1, sort_keys=True)
        fh.write("\n")


if __name__ == "__main__":
    sys.exit(main())
